"""Nemesis packages: fault schedules bundled with their nemeses.

Equivalent of jepsen.nemesis.combined's packages as the reference composes
them (nemesis.clj:24-58): a package = nemesis + main-phase generator +
final (healing) generator + perf annotation. `setup_nemesis` parses the
fault spec (``partition,kill`` / ``all`` / ``hell`` / ``none``,
nemesis.clj:8-29), builds one package per fault, and composes them.

Schedule shape mirrors the combined packages: each fault alternates
inject/heal (FlipFlop) with ≥interval seconds between ops (Delay), target
kinds drawn per-op from the reference's victim-class lists
(nemesis.clj:48-58).
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Optional, Sequence

from ..generator.base import (Delay, FlipFlop, Generator, Mix, OpFn, Seq,
                              Sleep)
from .base import Nemesis, NoopNemesis, compose_nemeses
from .faults import KillNemesis, PartitionNemesis, PauseNemesis
from .membership import GrowUntilFull, MemberNemesis
from .targets import NODE_TARGETS, PARTITION_TARGETS

FAULTS = ("pause", "kill", "partition", "member")
SPECIALS = {
    "none": (),
    "all": ("pause", "kill", "partition"),
    # member+kill can legitimately wedge the cluster (a majority of the
    # *current* membership can be dead) — same caveat as nemesis.clj:18-22.
    "hell": ("pause", "kill", "partition", "member"),
}

#: Workload-paired schedules (ISSUE 10 satellite): named fault schedules
#: tuned to what actually stresses a scenario, selectable like faults
#: (--nemesis set-churn) and auto-suggested by their workloads
#: (core/compose.py). Each rides the existing nemeses — so the PR-2
#: fault↔heal pairing analyzer's guarantees carry over unchanged.
SCHEDULES = ("set-churn", "queue-drain")


def parse_nemesis_spec(spec) -> tuple:
    """Comma-separated fault list or special name -> fault tuple
    (nemesis.clj:24-29)."""
    if spec is None:
        return ()
    if isinstance(spec, (list, tuple, set, frozenset)):
        faults = tuple(spec)
    else:
        s = str(spec).strip()
        if s in SPECIALS:
            return SPECIALS[s]
        faults = tuple(f.strip() for f in s.split(",") if f.strip())
    for f in faults:
        if f in SPECIALS and len(faults) == 1:
            return SPECIALS[f]
        if f not in FAULTS and f not in SCHEDULES:
            raise ValueError(
                f"unknown fault {f!r}; valid: {FAULTS}, schedules "
                f"{SCHEDULES}, or {tuple(SPECIALS)}")
    return faults


#: per-fault completion-ambiguity pressure at the reference interval
#: (5s): how strongly each fault turns live client ops into crashed
#: (info) ops in a recorded history. kill/partition sever clients
#: mid-op; pause merely delays; schedules churn specific workloads.
_FAULT_PRESSURE = {
    "kill": 0.08,
    "partition": 0.06,
    "pause": 0.03,
    "member": 0.05,
    "set-churn": 0.08,
    "queue-drain": 0.06,
}


def schedule_pressure(spec, interval: float) -> dict:
    """Map a nemesis spec + firing interval onto synthetic-history
    pressure (ISSUE 20): offline scenario search can't run the live
    fault injectors, but the *observable* effect of a schedule on a
    history is its crashed-op density — each fault contributes its
    reference pressure scaled by firing rate (5.0/interval), capped so
    the generator's concurrency window stays checkable. Returns
    {"crash_bias": float, "crash_burst": int} for
    `history/synth.random_valid_history`'s crash_p / max_crashes."""
    faults = parse_nemesis_spec(spec)
    rate = 5.0 / max(0.5, float(interval))
    bias = sum(_FAULT_PRESSURE.get(f, 0.04) for f in faults) * rate
    return {"crash_bias": round(min(0.4, bias), 4),
            "crash_burst": min(4, len(faults))}


@dataclass
class Package:
    """One fault's bundle (jepsen.nemesis.combined package map)."""

    nemesis: Nemesis
    generator: Optional[Generator] = None
    final_generator: Optional[Generator] = None
    #: perf-plot annotations, each {"name", "start" fs, "stop" fs,
    #: "color"} — uniformly a list so composition is concatenation.
    perf: list = field(default_factory=list)


def _targeted(f: str, kinds: Sequence[str], rng: random.Random) -> OpFn:
    return OpFn(lambda test, ctx: {"f": f, "value": rng.choice(list(kinds))})


def partition_package(opts: dict, db, net,
                      rng: random.Random) -> Package:
    interval = float(opts.get("interval", 5.0))
    gen = Delay(interval, FlipFlop(
        _targeted("start-partition", PARTITION_TARGETS, rng),
        OpFn(lambda test, ctx: {"f": "stop-partition"})))
    return Package(
        nemesis=PartitionNemesis(net, db, seed=rng.randrange(2**31)),
        generator=gen,
        final_generator=Seq([{"f": "stop-partition"}]),
        perf=[{"name": "partition", "start": {"start-partition"},
               "stop": {"stop-partition"}, "color": "#E9A447"}],
    )


def kill_package(opts: dict, db, rng: random.Random) -> Package:
    interval = float(opts.get("interval", 5.0))
    gen = Delay(interval, FlipFlop(
        _targeted("kill", NODE_TARGETS, rng),
        OpFn(lambda test, ctx: {"f": "restart"})))
    return Package(
        nemesis=KillNemesis(db, seed=rng.randrange(2**31)),
        generator=gen,
        final_generator=Seq([{"f": "restart", "value": "all"}]),
        perf=[{"name": "kill", "start": {"kill"}, "stop": {"restart"},
               "color": "#E0584F"}],
    )


def pause_package(opts: dict, db, rng: random.Random) -> Package:
    interval = float(opts.get("interval", 5.0))
    gen = Delay(interval, FlipFlop(
        _targeted("pause", NODE_TARGETS, rng),
        OpFn(lambda test, ctx: {"f": "resume"})))
    return Package(
        nemesis=PauseNemesis(db, seed=rng.randrange(2**31)),
        generator=gen,
        final_generator=Seq([{"f": "resume", "value": "all"}]),
        perf=[{"name": "pause", "start": {"pause"}, "stop": {"resume"},
               "color": "#6A51A3"}],
    )


def member_package(opts: dict, db, rng: random.Random) -> Package:
    interval = float(opts.get("interval", 5.0))
    gen = Delay(interval, FlipFlop(
        OpFn(lambda test, ctx: {"f": "shrink"}),
        OpFn(lambda test, ctx: {"f": "grow"})))
    return Package(
        nemesis=MemberNemesis(db, seed=rng.randrange(2**31)),
        generator=gen,
        # membership.clj:142-157: grow until full again (time-bounded by
        # the caller's final-phase budget). PACED: a grow that fails
        # instantly (no alive member mid-heal, stalled SUT) would
        # otherwise spin the final phase into an unbounded info-op spray
        # — a starved TSAN soak recorded 101k grow attempts in one run
        # (round-5 finding); retrying ~4×/s heals just as fast and
        # bounds the history.
        final_generator=Delay(0.25, GrowUntilFull()),
        perf=[{"name": "member", "start": {"shrink"}, "stop": {"grow"},
               "color": "#3C8031"}],
    )


def set_churn_package(opts: dict, db, rng: random.Random) -> Package:
    """Membership churn paired with the set workload's fill (ISSUE 10):
    shrink/grow at TWICE the configured fault rate, so acknowledged adds
    race view changes — the schedule that loses elements on a SUT whose
    snapshot/catch-up path is buggy. Same MemberNemesis + GrowUntilFull
    healing discipline as the stock member package (the fault↔heal
    pairing analyzer's coverage carries over unchanged)."""
    interval = max(0.5, float(opts.get("interval", 5.0)) / 2.0)
    gen = Delay(interval, FlipFlop(
        OpFn(lambda test, ctx: {"f": "shrink"}),
        OpFn(lambda test, ctx: {"f": "grow"})))
    return Package(
        nemesis=MemberNemesis(db, seed=rng.randrange(2**31)),
        generator=gen,
        final_generator=Delay(0.25, GrowUntilFull()),
        perf=[{"name": "set-churn", "start": {"shrink"}, "stop": {"grow"},
               "color": "#3C8031"}],
    )


def queue_drain_package(opts: dict, db, net,
                        rng: random.Random) -> Package:
    """Partition paired with the queue workload's drain (ISSUE 10): the
    fill phase runs clean long enough to build a backlog (first fault
    delayed a full interval past the standard nemesis warm-up), then
    majority-flavored partitions flip during the drain — the schedule
    that double-delivers or loses tickets on a SUT whose leader handoff
    re-serves a popped head. Same PartitionNemesis + stop-partition
    healing as the stock package."""
    interval = float(opts.get("interval", 5.0))
    drain_targets = ("majority", "majorities-ring", "primaries")
    gen = Seq([Sleep(interval),
               Delay(interval, FlipFlop(
                   _targeted("start-partition", drain_targets, rng),
                   OpFn(lambda test, ctx: {"f": "stop-partition"})))])
    return Package(
        nemesis=PartitionNemesis(net, db, seed=rng.randrange(2**31)),
        generator=gen,
        final_generator=Seq([{"f": "stop-partition"}]),
        perf=[{"name": "queue-drain", "start": {"start-partition"},
               "stop": {"stop-partition"}, "color": "#E9A447"}],
    )


def compose_packages(packages: Sequence[Package],
                     seed: Optional[int] = None) -> Package:
    pkgs = [p for p in packages if p is not None]
    if not pkgs:
        return Package(nemesis=NoopNemesis())
    gens = [p.generator for p in pkgs if p.generator is not None]
    finals = [p.final_generator for p in pkgs if p.final_generator is not None]
    return Package(
        nemesis=compose_nemeses([p.nemesis for p in pkgs]),
        generator=Mix(gens, seed=seed) if gens else None,
        final_generator=Seq(finals) if finals else None,
        perf=[a for p in pkgs for a in p.perf],
    )


def setup_nemesis(opts: dict, db, net=None,
                  seed: Optional[int] = None) -> Package:
    """Fault spec -> composed package (nemesis.clj:48-58)."""
    faults = parse_nemesis_spec(opts.get("nemesis"))
    rng = random.Random(seed)
    pkgs = []
    for f in faults:
        if f == "partition":
            if net is None:
                raise ValueError("partition fault requires a Net")
            pkgs.append(partition_package(opts, db, net, rng))
        elif f == "kill":
            pkgs.append(kill_package(opts, db, rng))
        elif f == "pause":
            pkgs.append(pause_package(opts, db, rng))
        elif f == "member":
            pkgs.append(member_package(opts, db, rng))
        elif f == "set-churn":
            pkgs.append(set_churn_package(opts, db, rng))
        elif f == "queue-drain":
            if net is None:
                raise ValueError("queue-drain schedule requires a Net")
            pkgs.append(queue_drain_package(opts, db, net, rng))
    return compose_packages(pkgs, seed=rng.randrange(2**31))

"""Nemesis protocol.

Equivalent of jepsen.nemesis/Nemesis (setup!/invoke!/teardown!) plus
composition by op kind (what jepsen.nemesis.combined/compose-packages does
for the reference at nemesis.clj:46).
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional

from ..history.ops import Op


class Nemesis:
    def setup(self, test: dict) -> "Nemesis":
        return self

    def invoke(self, test: dict, op: Op) -> Op:
        raise NotImplementedError

    def teardown(self, test: dict) -> None:
        return None

    #: op kinds (f values) this nemesis handles; used for composition.
    fs: Iterable[str] = ()


class NoopNemesis(Nemesis):
    def invoke(self, test, op):
        return op.replace(value="noop")


class ComposedNemesis(Nemesis):
    """Route ops to children by op.f."""

    def __init__(self, children: Iterable[Nemesis]):
        self.children = list(children)
        self.routes: Dict[str, Nemesis] = {}
        for c in self.children:
            for f in c.fs:
                if f in self.routes:
                    raise ValueError(f"two nemeses both handle f={f!r}")
                self.routes[f] = c

    def setup(self, test):
        self.children = [c.setup(test) for c in self.children]
        self.routes = {}
        for c in self.children:
            for f in c.fs:
                self.routes[f] = c
        return self

    def invoke(self, test, op):
        child = self.routes.get(op.f)
        if child is None:
            return op.replace(value=f"no nemesis handles {op.f}")
        return child.invoke(test, op)

    def teardown(self, test):
        for c in self.children:
            c.teardown(test)


def compose_nemeses(children: Iterable[Optional[Nemesis]]) -> Nemesis:
    kids = [c for c in children if c is not None]
    if not kids:
        return NoopNemesis()
    if len(kids) == 1:
        return kids[0]
    return ComposedNemesis(kids)

"""Request records for the checking service.

A submission enters graftd as raw history material (op dicts over the
wire, `history.ops.History` objects in-process, or a recorded-run dir)
and is normalized at ADMISSION into a `CheckRequest`: per-unit encoded
event tensors (`history.packing.encode_history` — encoded exactly once,
here), a content fingerprint over those tensors (the result-cache key:
two tenants submitting byte-identical histories share one verdict), and
scheduling metadata (deadline, priority, submit time). Everything
downstream — bucketing, coalescing, demux — works on the encodings; the
raw ops are kept only for the per-request trace record.
"""

from __future__ import annotations

import hashlib
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

import numpy as np

from ..checker.base import merge_valid
from ..history.ops import History, Op
from ..history.packing import EncodedHistory, encode_history

# Request lifecycle states.
QUEUED = "queued"
RUNNING = "running"
DONE = "done"
CANCELLED = "cancelled"
FAILED = "failed"

#: Priority clamp at admission: each unit is one second of deadline
#: credit (scheduler.PRIORITY_CREDIT_S), so ±8 bounds the head start at
#: ±8 s — well under the 30 s aging cap, keeping the documented
#: starvation-free guarantee true against a client-supplied flood of
#: arbitrarily large priorities.
MAX_PRIORITY = 8

#: workload name → (model factory, values are (key, value) tuples?).
#: The tuple-valued workloads are split per key at admission (the same
#: independent decomposition checker/recorded.py applies to stored
#: runs), so one submitted multi-register history becomes one check
#: unit per key. "register"/"counter" accept plain single-key histories
#: — the shape tests and the bench submit.
def service_workloads() -> dict:
    from ..models import CasRegister, Counter, GSet, ListAppend, TicketQueue

    return {
        "register": (CasRegister, False),
        "counter": (Counter, False),
        "single-register": (CasRegister, True),
        "multi-register": (CasRegister, True),
        "set": (GSet, False),
        "queue": (TicketQueue, False),
        "list-append": (ListAppend, True),
    }


def history_from_dicts(rows: Sequence[dict]) -> History:
    """Wire format → History: one op dict per row (`Op.to_dict` shape).
    JSON has no tuples, so list-valued ops (the independent workloads'
    (key, value) pairs) are retupled — same rule as `store.load_history`."""
    h = History()
    for d in rows:
        d = dict(d)
        if isinstance(d.get("value"), list):
            d["value"] = tuple(d["value"])
        h.append(Op.from_dict(d))
    return h


def fingerprint_encodings(model, algorithm: str,
                          encs: Sequence[EncodedHistory],
                          consistency: str = "linearizable") -> str:
    """Content hash over the packed arrays of a submission — the result
    cache key. Hashing the ENCODING (not the op dicts) makes the cache
    insensitive to wire-level noise that cannot change the verdict
    (timestamps, op indices of dropped fail ops) while staying sound:
    the encoded event stream is exactly the checker's input. The
    consistency rung is part of the identity: the same bytes checked at
    a weaker rung are a DIFFERENT verdict — and at a weaker rung the
    per-event process ids are hashed too, because the relaxation defers
    FORCEs along per-process order, so two submissions with identical
    event rows but different proc arrays genuinely have different
    verdicts there (at the linearizable rung proc is inert and stays
    out of the hash, preserving wire-noise insensitivity).

    Zero-copy (ISSUE 15 tentpole (d)): the packed int32 buffers feed
    sha256 through memoryviews — `hashlib.update` consumes any
    C-contiguous buffer directly, so the per-submission `tobytes()`
    copies of the (often multi-MB) event tensors are gone. The BYTES
    hashed are identical, so every digest value is unchanged — the
    content-addressed store and the WAL replay key on these values
    (pinned by the golden-fingerprint test)."""
    h = hashlib.sha256()
    h.update(type(model).__name__.encode())
    h.update(b"\x00")
    h.update(algorithm.encode())
    weak = consistency != "linearizable"
    if weak:
        h.update(b"\x00")
        h.update(consistency.encode())
    for e in encs:
        h.update(memoryview(np.asarray(e.events.shape, dtype=np.int64)))
        h.update(memoryview(np.ascontiguousarray(e.events)))
        h.update(np.int64(e.n_slots).data)
        if weak:
            h.update(b"\x01" if e.proc is not None else b"\x00")
            if e.proc is not None:
                h.update(memoryview(np.ascontiguousarray(
                    np.asarray(e.proc, dtype=np.int32))))
    return h.hexdigest()


@dataclass
class CheckRequest:
    """One tenant submission, admitted and encoded.

    units: (label, History) pairs — one frontier-check unit each (a
        plain submission is one unit per history; independent workloads
        contribute one unit per key).
    encs: the per-unit encodings, parallel to `units`.
    deadline/submitted: monotonic seconds (scheduling only — a missed
        deadline reorders, it never drops).
    results: per-unit checker result dicts once DONE.
    stats: batch-attribution stamped at demux (batched_requests,
        batch_rows, batch_seq, the launch's labeled scan-scope counters).
    """

    id: str
    workload: str
    model: object
    algorithm: str
    units: List[tuple]
    encs: List[EncodedHistory]
    fingerprint: str
    deadline: float
    submitted: float
    priority: int = 0
    #: consistency ladder rung (checker/consistency.py): part of the
    #: bucket signature (same-rung requests coalesce) and the result
    #: fingerprint; the checker relaxes per batch, so admission keeps
    #: the canonical linearizable encoding.
    consistency: str = "linearizable"
    status: str = QUEUED
    results: Optional[List[dict]] = None
    error: Optional[str] = None
    cached: bool = False
    stats: dict = field(default_factory=dict)
    cancelled: threading.Event = field(default_factory=threading.Event)
    #: durability/resilience lifecycle (ISSUE 8): executor deaths while
    #: this request's batch was in flight (quarantined past the crash
    #: cap), solo = excluded from coalescing (a poison batch is SPLIT so
    #: innocent riders complete alone), force_host = the hung-batch
    #: watchdog's second strike (re-run via check_encoded_host, never
    #: the device path), watchdog_hits = strikes so far, replayed = came
    #: back from the admission journal, attached_to = idempotent-dup
    #: follower of the named primary request.
    crash_count: int = 0
    solo: bool = False
    force_host: bool = False
    watchdog_hits: int = 0
    #: monotonic time the CURRENT execution began (scheduler.execute
    #: stamps it beside the RUNNING flip). The watchdog strikes only
    #: when the EXECUTION has been running past the margin — a request
    #: that merely waited out its deadline in a backlogged queue is
    #: late, not hung, and demoting healthy workers for it would
    #: amplify the overload.
    run_started: float = 0.0
    replayed: bool = False
    attached_to: Optional[str] = None
    #: transactional-anomaly overlay (ISSUE 19): stamped at ADMISSION
    #: for txn_anomaly_capable models (list-append) from the UNDECOMPOSED
    #: multi-key histories — the per-key units cannot see cross-key
    #: cycles, and the fingerprint hashes only per-unit encodings, so
    #: this rides outside the result cache on purpose: a cached unit
    #: result-set stays reusable while the overlay is recomputed per
    #: submission (two submissions CAN share per-key encodings yet
    #: differ in cross-key session order). The binary lane
    #: (admit_encoded) ships encodings only, so it has no overlay.
    txn_anomalies: Optional[dict] = None
    _done: threading.Event = field(default_factory=threading.Event)
    _finish_lock: threading.Lock = field(default_factory=threading.Lock)

    @property
    def n_rows(self) -> int:
        return len(self.encs)

    @property
    def terminal(self) -> bool:
        """True once a terminal state landed (first-wins `finish`)."""
        return self._done.is_set()

    def verdict(self):
        """Merged validity over the request's units (checker.base rule:
        any INVALID → INVALID, else any non-VALID → UNKNOWN), folded
        with the admission-time transactional-anomaly overlay — a
        cross-key G0/G1c/G-single refutes the submission even when
        every per-key unit passes its rung."""
        if self.results is None:
            return None
        base = merge_valid(r.get("valid?") for r in self.results)
        if self.txn_anomalies is not None:
            return merge_valid([base,
                                self.txn_anomalies.get("valid?", True)])
        return base

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until the request reaches a terminal state."""
        return self._done.wait(timeout)

    def finish(self, status: str, results: Optional[List[dict]] = None,
               error: Optional[str] = None) -> bool:
        # FIRST terminal state wins (returns False on a late loser): a
        # hung batch the watchdog requeued executes at-least-once, and
        # whichever execution finishes first owns the client-visible
        # result — the stale twin's finish must not overwrite it
        # (at-most-once client-visible result, doc/checker-design.md
        # §11). Results/error land BEFORE the terminal status: a
        # concurrent reader polling `status` (the HTTP surface's
        # to_dict without wait_s) must never observe a terminal state
        # whose results are still missing.
        with self._finish_lock:
            if self._done.is_set():
                return False
            self.results = results
            self.error = error
            self.status = status
            self._done.set()
            return True

    def to_dict(self, include_results: bool = True) -> dict:
        d = {
            "id": self.id,
            "status": self.status,
            "workload": self.workload,
            "algorithm": self.algorithm,
            "consistency": self.consistency,
            "units": [label for label, _ in self.units],
            "fingerprint": self.fingerprint,
            "priority": self.priority,
            "cached": self.cached,
        }
        if self.error is not None:
            d["error"] = self.error
        if self.replayed:
            d["replayed"] = True
        if self.attached_to is not None:
            d["attached_to"] = self.attached_to
        if self.stats:
            d["service-stats"] = dict(self.stats)
        if self.txn_anomalies is not None:
            d["txn-anomalies"] = self.txn_anomalies
        if include_results and self.results is not None:
            d["valid?"] = self.verdict()
            d["results"] = self.results
        return d


def build_units(histories: Sequence, workload: str):
    """Normalize raw submission material into (model, units): the
    workload's model instance plus (label, History) pairs — one
    frontier-check unit each, independent workloads split per key.
    ONE home for the unit decomposition, shared by server-side `admit`
    and the binary lane's CLIENT-side encoder (ISSUE 18): both sides
    must derive identical unit lists from identical histories, or the
    server-derived fingerprint would diverge from the JSON path's."""
    workloads = service_workloads()
    if workload not in workloads:
        raise ValueError(f"unknown workload {workload!r} "
                         f"(have: {', '.join(sorted(workloads))})")
    model_factory, independent = workloads[workload]
    model = model_factory()
    units: List[tuple] = []
    for i, h in enumerate(histories):
        if not isinstance(h, History):
            h = history_from_dicts(h)
        h = h.client_ops()
        if independent:
            from ..checker.independent import split_by_key

            for key, sub in sorted(split_by_key(h).items(),
                                   key=lambda kv: str(kv[0])):
                units.append((f"h{i}/key={key}", sub))
        else:
            units.append((f"h{i}", h))
    if not units:
        raise ValueError("empty submission: no checkable history units")
    return model, units


def admit(histories: Sequence, workload: str, algorithm: str = "auto",
          deadline_ms: Optional[float] = None, priority: int = 0,
          default_deadline_s: float = 3600.0,
          request_id: Optional[str] = None,
          consistency: str = "linearizable") -> CheckRequest:
    """Normalize a submission into a CheckRequest (encode once +
    fingerprint). `histories` items are History objects or op-dict
    lists. Raises ValueError on unknown workloads / malformed ops /
    unknown consistency rungs — the HTTP surface maps that to 400,
    never into the queue."""
    from ..checker.consistency import normalize_consistency

    consistency = normalize_consistency(consistency)
    model, units = build_units(histories, workload)
    encs = [encode_history(h, model) for _, h in units]
    txn = None
    if getattr(model, "txn_anomaly_capable", False):
        # host-only (kernel=False inside): Tarjan + numpy closure on
        # the admission thread, never a device launch
        from ..checker.anomaly import certify_submission

        txn = certify_submission([
            (h if isinstance(h, History) else
             history_from_dicts(h)).client_ops()
            for h in histories])
    now = time.monotonic()  # admission timestamp (txn overlay above)
    deadline = now + (deadline_ms / 1000.0 if deadline_ms is not None
                      else default_deadline_s)
    return CheckRequest(
        id=request_id or uuid.uuid4().hex[:12],
        workload=workload,
        model=model,
        algorithm=algorithm,
        units=units,
        encs=encs,
        fingerprint=fingerprint_encodings(model, algorithm, encs,
                                          consistency),
        deadline=deadline,
        submitted=now,
        priority=clamp_priority(priority),
        consistency=consistency,
        txn_anomalies=txn,
    )


def admit_encoded(workload: str, labels: Sequence[str],
                  encs: Sequence[EncodedHistory],
                  algorithm: str = "auto",
                  deadline_ms: Optional[float] = None, priority: int = 0,
                  default_deadline_s: float = 3600.0,
                  consistency: str = "linearizable",
                  claimed_fingerprint: Optional[str] = None) -> CheckRequest:
    """Admit a CLIENT-encoded submission (the binary frame lane, ISSUE
    18): the per-unit encodings arrive already packed, so admission
    skips the encode entirely — but NEVER the fingerprint. The digest
    is re-derived here over the received tensor bytes, exactly the
    computation the JSON path runs on its own encode output, so a
    client lying about its payload (or its claimed fingerprint) can
    only corrupt its own verdict: every cache/store/WAL key is the
    server-derived value (doc/checker-design.md §20). A claimed
    fingerprint that disagrees is recorded in the request's stats
    (operators can alarm on it) and otherwise ignored.

    Like journal replay (`journal.decode_request`), the units carry
    empty History placeholders — raw ops stay client-side by design,
    so the trace record has no history.jsonl and counterexample
    minimization is skipped for frame submissions."""
    from ..checker.consistency import normalize_consistency

    consistency = normalize_consistency(consistency)
    workloads = service_workloads()
    if workload not in workloads:
        raise ValueError(f"unknown workload {workload!r} "
                         f"(have: {', '.join(sorted(workloads))})")
    model = workloads[workload][0]()
    if not encs:
        raise ValueError("empty submission: no checkable history units")
    if len(labels) != len(encs):
        raise ValueError(f"{len(labels)} labels for {len(encs)} "
                         "encodings")
    fingerprint = fingerprint_encodings(model, algorithm, encs,
                                        consistency)
    now = time.monotonic()
    deadline = now + (deadline_ms / 1000.0 if deadline_ms is not None
                      else default_deadline_s)
    req = CheckRequest(
        id=uuid.uuid4().hex[:12],
        workload=workload,
        model=model,
        algorithm=algorithm,
        units=[(str(label), History()) for label in labels],
        encs=list(encs),
        fingerprint=fingerprint,
        deadline=deadline,
        submitted=now,
        priority=clamp_priority(priority),
        consistency=consistency,
    )
    if claimed_fingerprint is not None \
            and claimed_fingerprint != fingerprint:
        # keyed on the server's digest regardless; the mismatch is
        # evidence, not an error (a 400 would let a prober distinguish
        # digests it does not hold the preimage of)
        req.stats["fingerprint_mismatch"] = True
    return req


def clamp_priority(priority) -> int:
    return max(-MAX_PRIORITY, min(MAX_PRIORITY, int(priority)))


def admit_run_dir(run_dir, algorithm: str = "auto",
                  deadline_ms: Optional[float] = None, priority: int = 0,
                  workload: Optional[str] = None,
                  default_deadline_s: float = 3600.0,
                  consistency: str = "linearizable") -> CheckRequest:
    """Admit a recorded-run directory (store/<name>/<ts>/): load the
    stored history, split per key exactly like `checker/recorded.py`,
    and check it as one request. The service's re-verification surface
    for artifacts a live run already produced."""
    from ..checker.consistency import normalize_consistency
    from ..checker.recorded import load_run_histories
    from ..models.base import Model

    consistency = normalize_consistency(consistency)
    model, subs, wl = load_run_histories(run_dir, workload)
    if not isinstance(model, Model):
        raise ValueError(
            f"{run_dir}: workload {wl!r} uses a non-frontier checker; "
            "re-verify it with `python -m jepsen_jgroups_raft_tpu check`")
    units = [(f"{wl}/u{i}", h) for i, h in enumerate(subs)]
    encs = [encode_history(h, model) for _, h in units]
    now = time.monotonic()
    deadline = now + (deadline_ms / 1000.0 if deadline_ms is not None
                      else default_deadline_s)
    return CheckRequest(
        id=uuid.uuid4().hex[:12],
        workload=wl,
        model=model,
        algorithm=algorithm,
        units=units,
        encs=encs,
        fingerprint=fingerprint_encodings(model, algorithm, encs,
                                          consistency),
        deadline=deadline,
        submitted=now,
        priority=clamp_priority(priority),
        consistency=consistency,
    )

"""Streaming verdict sessions: crash-resumable online checking
(ISSUE 12 tentpole).

A batch submission is a complete history or nothing; a *stream session*
is a history checked WHILE it is produced. The client opens a session,
appends op segments carrying client-assigned monotonically increasing
sequence numbers, and reads the session's live verdict from every
append response (and ``/stream/status``): a violation surfaces at the
earliest segment where it becomes decidable — mid-run, with a minimized
counterexample — not at ``/stream/finish``.

The machinery is the staged substrate re-entered across process and
segment boundaries:

* **Incremental encoding** — `history.packing.IncrementalEncoder` emits
  the SETTLED suffix of the one-shot event stream per append (an op's
  event content is final once its completion is recorded; settled
  events are prefix-stable under appends).
* **Resumable certifier fast path** — the value-guided
  bounded-backtrack certifier runs per segment as a RESUMABLE carry
  (`checker.consistency.StreamingCertifier`, ISSUE 14): its (state,
  done-set, pending, backtrack frame) persists between appends next to
  the kernel carry below, so an append costs O(segment) instead of the
  per-append restart's O(history). Most valid sessions never launch a
  kernel at all; certifications are tier-stamped ``greedy@lin`` /
  ``backtrack@lin`` (a stream session certifies the linearizable
  rung).
* **Carried chunk scan** — once greedy declines (or the stream outgrows
  its cap), `checker.schedule.CarriedScan` owns the chunked wavefront's
  ``{inner, left}`` carry BETWEEN appends: each segment's new events
  advance the same scan-step sequence one uninterrupted scan would run,
  so mid-stream flags are the frozen-verdict flags — ``~ok ∧
  ~overflow`` is a FINAL violation (the unit is evicted: carry, events,
  and ops freed, which is what bounds memory for unbounded histories),
  ``~ok ∧ overflow`` escalates to the full ladder at finish.
* **Durability** — every open/segment/finish is journaled (CRC'd,
  fsync'd BEFORE the 2xx) into the PR 8 WAL under its own record
  family. A daemon restart (or a PR 11 cross-replica claim) restores
  sessions as parked *resumable* stubs; the first touch replays the
  journaled segments through the identical deterministic pipeline, so
  the resumed verdict is bitwise-identical to an uninterrupted run
  (doc/checker-design.md §14).
* **Flow control** — per-session segment-rate and byte budgets answer
  429 + Retry-After (a runaway producer must not starve batch
  admission); sessions idle past ``JGRAFT_STREAM_IDLE_S`` are parked
  as incomplete (memory freed, journal kept, resumable).

Consistency: streaming serves the LINEARIZABLE rung only. The weaker
rungs relax FORCE placement using per-process *future* structure
(`checker/consistency.py` defers a FORCE toward the process's next
op), which is not prefix-stable — a weaker-rung open is rejected with
400 rather than served unsoundly.
"""

from __future__ import annotations

import hashlib
import json
import logging
import threading
import time
import uuid
from typing import List, Optional, Sequence

import numpy as np

from ..checker.base import INVALID, VALID, merge_valid
from ..checker.schedule import CarriedScan
from ..history.ops import NEMESIS, History, Op
from ..history.packing import EncodedHistory, IncrementalEncoder
from ..platform import env_float, env_int
from .journal import (decode_stream_bseg_units, encode_stream_bseg,
                      encode_stream_fin, encode_stream_open,
                      encode_stream_segment)

LOG = logging.getLogger("jgraft.service")

# Session lifecycle states.
OPEN = "open"
INCOMPLETE = "incomplete"   # parked (idle/restart); resumable from WAL
DONE = "done"
FAILED = "failed"


def sessions_cap() -> int:
    """Concurrent live sessions (JGRAFT_STREAM_SESSIONS, default 64).
    Past the cap `/stream/open` answers 429 — the same
    reject-don't-buffer stance as the admission queue."""
    return env_int("JGRAFT_STREAM_SESSIONS", 64, minimum=1)


def idle_timeout_s() -> float:
    """Idle bound (JGRAFT_STREAM_IDLE_S, default 600 s; 0 disables):
    a session untouched this long is finalized-as-incomplete — memory
    freed, journal kept, resumable by the next append."""
    return env_float("JGRAFT_STREAM_IDLE_S", 600.0, minimum=0.0)


def segments_per_s() -> float:
    """Per-session append-rate budget (JGRAFT_STREAM_SEGS_PER_S,
    default 200/s; 0 disables)."""
    return env_float("JGRAFT_STREAM_SEGS_PER_S", 200.0, minimum=0.0)


def bytes_per_s() -> float:
    """Per-session byte budget (JGRAFT_STREAM_BYTES_PER_S, default
    16 MiB/s; 0 disables)."""
    return env_float("JGRAFT_STREAM_BYTES_PER_S", float(16 << 20),
                     minimum=0.0)


def greedy_max_events() -> int:
    """Settled-stream size up to which the per-segment greedy certifier
    carries a unit (JGRAFT_STREAM_GREEDY_MAX_EVENTS, default 8192).
    The greedy pass is O(E·W) per segment; past the cap the unit
    engages the carried kernel, whose per-append cost is O(new)."""
    return env_int("JGRAFT_STREAM_GREEDY_MAX_EVENTS", 8192, minimum=0)


def resident_events_cap() -> int:
    """Per-unit resident settled-event bound
    (JGRAFT_STREAM_RESIDENT_EVENTS, default 1M rows ≈ 20 MB). Beyond
    it the unit SPILLS: host buffers are dropped (the journal already
    holds every segment) and only the O(1) kernel carry stays resident
    — a carry rebuild or finish-escalation replays from the WAL."""
    return env_int("JGRAFT_STREAM_RESIDENT_EVENTS", 1 << 20, minimum=1)


class StreamBusy(Exception):
    """Flow-control rejection (HTTP 429 + Retry-After)."""

    def __init__(self, msg: str, retry_after_s: float):
        super().__init__(msg)
        self.retry_after_s = round(max(0.1, retry_after_s), 2)


class StreamConflict(Exception):
    """Sequencing/state conflict (HTTP 409). `expected_seq` tells a
    well-behaved client where the session actually is."""

    def __init__(self, msg: str, expected_seq: Optional[int] = None):
        super().__init__(msg)
        self.expected_seq = expected_seq


class _Parked(Exception):
    """Internal: a mutating call raced the idle reaper's park() and
    holds a freed session object. The manager catches this and retries
    against the revived session — the race costs one WAL replay, never
    a client-visible error."""


def segment_digest(unit_ops) -> str:
    """Idempotency key of one segment payload: a duplicate append (the
    backoff-retrying client re-sending a seq whose 2xx was lost) must
    carry the SAME payload; a different payload under a reused seq is a
    client bug answered 409, never silently merged."""
    return hashlib.sha256(json.dumps(
        unit_ops, sort_keys=True, default=str).encode()).hexdigest()


def binary_segment_digest(units_payload) -> str:
    """Idempotency key of a BINARY segment (ISSUE 18) when the caller
    has no raw frame bytes to hash (direct API use, journal replay of a
    record appended that way). The HTTP surface hashes the received
    frame bytes instead — either way the journaled digest is what a
    post-crash duplicate compares against, and a client retry resends
    identical content."""
    h = hashlib.sha256()
    for u in units_payload:
        for key in ("n_slots", "n_ops", "consumed"):
            h.update(str(int(u[key])).encode())
        h.update(b"\x01" if u.get("final") else b"\x00")
        h.update(np.ascontiguousarray(u["events"], dtype=np.int32)
                 .tobytes())
        h.update(np.ascontiguousarray(u["op_index"], dtype=np.int32)
                 .tobytes())
        if u.get("proc") is not None:
            h.update(np.ascontiguousarray(u["proc"], dtype=np.int32)
                     .tobytes())
    return h.hexdigest()


class _TokenBucket:
    """Minimal per-session budget: `rate` tokens/s, burst = 2 s worth.
    0 rate disables."""

    def __init__(self, rate: float):
        self.rate = float(rate)
        self.burst = max(self.rate * 2.0, 1.0)
        self.level = self.burst
        self.at = time.monotonic()

    def take(self, n: float) -> Optional[float]:
        """Consume `n` tokens; None on success, else seconds until the
        deficit refills (the Retry-After hint)."""
        if self.rate <= 0:
            return None
        now = time.monotonic()
        self.level = min(self.burst, self.level + (now - self.at) * self.rate)
        self.at = now
        if self.level >= n:
            self.level -= n
            return None
        return (n - self.level) / self.rate


class _BinaryEnc:
    """Counter stand-in for a binary-lane unit's `enc` slot (ISSUE 18):
    the REAL incremental encoder runs on the CLIENT; the server's
    decision ladder only reads the cumulative counters (``n_slots`` /
    ``n_ops`` / ``n_events`` / ``consumed``) this mirror accumulates
    from segment headers. Counters are folded with max() so they stay
    monotone even against a confused client — which, like a lying
    fingerprint claim, can only corrupt its own verdict."""

    def __init__(self):
        self.n_slots = 0
        self.n_ops = 0
        self.n_events = 0
        self.consumed = 0


class StreamUnit:
    """One streamed history row: its incremental encoder, resident
    settled stream, and whichever decision engine currently carries it
    (greedy witness, then the carried kernel)."""

    def __init__(self, model):
        self.model = model
        self.enc = IncrementalEncoder(model)
        #: binary lane: the client's final flush arrived (`final=true`
        #: segment). A binary finish REQUIRES it — without the flush
        #: the crashed-pair OPEN events of outstanding invokes are
        #: missing, and dropping linearization candidates can turn a
        #: valid history into a false INVALID.
        self.bin_final = False
        # resident settled stream (dropped on spill / decide)
        self._events: List[np.ndarray] = []
        self._op_index: List[np.ndarray] = []
        self._proc: List[np.ndarray] = []
        self.events_resident = 0
        #: settled suffixes not yet fed to the carried scan. Survives a
        #: spill (the resident buffers do not), so post-spill segments
        #: still advance the carry — dropping them would freeze the
        #: scan on the pre-spill prefix and report a false VALID.
        self.pending: List[np.ndarray] = []
        self.ops: List[Op] = []       # raw rows (counterexample budget)
        self.ops_total = 0
        self.greedy = True            # greedy fast path still carries
        self.certified = False        # greedy proved the settled prefix
        self.certify_tier = None      # "greedy@lin"/"backtrack@lin"
        #: resumable certifier carry (ISSUE 14): the witness scan's
        #: (state, done-set, pending, backtrack stack) owned BETWEEN
        #: appends next to the kernel carry below — per-append certify
        #: cost is O(segment), not the PR-13 restart's O(history).
        #: Rebuilt deterministically on replay like the kernel carry.
        self.certifier = None         # consistency.StreamingCertifier
        #: settled suffixes not yet fed to the certifier (the carry's
        #: feed-queue twin; cleared when the unit leaves the greedy
        #: path).
        self.cert_queue: List[np.ndarray] = []
        self.scan: Optional[CarriedScan] = None
        self.spilled = False
        self.escalated = False        # needs the full ladder at finish
        self.result: Optional[dict] = None   # final per-unit verdict
        self.decided_seq: Optional[int] = None

    # ------------------------------------------------------- accessors

    @property
    def decided(self) -> bool:
        return self.result is not None

    def settled_events(self) -> np.ndarray:
        if self._events and len(self._events) > 1:
            self._events = [np.concatenate(self._events)]
        return self._events[0] if self._events else \
            np.zeros((0, 5), np.int32)

    def settled_encoding(self) -> EncodedHistory:
        ev = self.settled_events()
        oi = (np.concatenate(self._op_index) if self._op_index
              else np.zeros((0,), np.int32))
        pr = (np.concatenate(self._proc) if self._proc
              else np.zeros((0,), np.int32))
        return EncodedHistory(events=ev, op_index=oi,
                              n_slots=self.enc.n_slots,
                              n_ops=self.enc.n_ops, proc=pr)

    def free(self) -> None:
        """Eviction: a decided row's buffers and carry are dead weight
        (the frozen verdict cannot change) — this is what keeps an
        unbounded session's memory bounded."""
        self._events = []
        self._op_index = []
        self._proc = []
        self.events_resident = 0
        self.pending = []
        self.ops = []
        self.scan = None
        self.certifier = None
        self.cert_queue = []
        self.enc = None

    def drain_pending(self) -> None:
        """Feed every not-yet-scanned settled suffix into the carry
        (stopping at a frozen verdict) and clear the queue."""
        for ev in self.pending:
            if self.scan is None or self.scan.decided:
                break
            self.scan.feed(ev)
        self.pending = []

    # -------------------------------------------------------- pipeline

    def ingest(self, ops: Sequence[Op], final: bool = False) -> None:
        """Feed raw rows through the incremental encoder. The settled
        suffix always enters `pending` (the carry's feed queue); the
        resident buffers additionally retain it unless spilled."""
        if self.decided:
            return
        ev, oi, pr = self.enc.feed(ops, final=final)
        self.ops_total += len(ops)
        if not self.spilled and self.ops_total <= MAX_COUNTEREXAMPLE_OPS:
            self.ops.extend(ops)
        if ev.shape[0]:
            self.pending.append(ev)
            if self.greedy:
                self.cert_queue.append(ev)
            if not self.spilled:
                self._events.append(ev)
                self._op_index.append(oi)
                self._proc.append(pr)
                self.events_resident += int(ev.shape[0])

    def ingest_encoded(self, u: dict) -> None:
        """Binary-lane twin of `ingest` (ISSUE 18): the client ran the
        incremental encoder; this applies its already-normalized
        settled-suffix payload (`StreamSession._parse_bseg_units`) and
        cumulative counters. No raw rows exist server-side — `ops`
        stays empty, so escalation and carry rebuilds use the settled
        stream or the WAL's bseg arrays, and violations ship without a
        minimized counterexample (the same trade journal replay makes)."""
        if self.decided:
            return
        enc = self.enc   # _BinaryEnc (installed by the mode latch)
        enc.n_slots = max(enc.n_slots, u["n_slots"])
        enc.n_ops = max(enc.n_ops, u["n_ops"])
        enc.consumed = max(enc.consumed, u["consumed"])
        self.ops_total = enc.consumed
        if u["final"]:
            self.bin_final = True
        ev, oi, pr = u["events"], u["op_index"], u["proc"]
        if pr is None:
            pr = np.zeros(int(ev.shape[0]), np.int32)
        if ev.shape[0]:
            enc.n_events += int(ev.shape[0])
            self.pending.append(ev)
            if self.greedy:
                self.cert_queue.append(ev)
            if not self.spilled:
                self._events.append(ev)
                self._op_index.append(oi)
                self._proc.append(pr)
                self.events_resident += int(ev.shape[0])


#: Counterexample-minimization budget (the scheduler's bound): beyond
#: this many raw rows the violation ships without a minimized witness.
MAX_COUNTEREXAMPLE_OPS = 2048


class StreamSession:
    """One live session. All mutation happens under `lock` (appends to
    DIFFERENT sessions run concurrently on their handler threads — the
    same thread discipline as the daemon's shard executors)."""

    def __init__(self, manager, sid: str, workload: str, model,
                 algorithm: str, consistency: str, n_units: int):
        self.manager = manager
        self.sid = sid
        self.workload = workload
        self.model = model
        self.algorithm = algorithm
        self.consistency = consistency
        self.units = [StreamUnit(model) for _ in range(n_units)]
        self.lock = threading.RLock()
        self.status = OPEN  # guarded_by(lock)
        #: transport lane, latched at the first accepted segment:
        #: "json" (raw op dicts, server-side encoder) or "binary"
        #: (client-encoded settled suffixes — ISSUE 18). Mixing lanes
        #: mid-session is a 409: the two lanes journal different record
        #: kinds and rebuild through different pipelines, and a replay
        #: must walk exactly one of them.
        self.mode: Optional[str] = None  # guarded_by(lock)
        self.error: Optional[str] = None
        self.final: Optional[dict] = None
        self.seq_next = 1  # guarded_by(lock)
        # seq -> payload digest
        self.seen: dict = {}  # guarded_by(lock)
        self.segments = 0
        self.bytes = 0
        self.opened = time.monotonic()
        self.last_touch = time.monotonic()
        self.resumed = False
        # journal replay in progress
        self._replaying = False  # guarded_by(lock)
        self._seg_bucket = _TokenBucket(segments_per_s())
        self._byte_bucket = _TokenBucket(bytes_per_s())

    # ------------------------------------------------------ validation

    def _parse_units(self, unit_ops) -> List[List[Op]]:
        """Wire payload → per-unit Op rows. Accepts a flat op list for
        single-unit sessions or one list per unit; nemesis rows are
        filtered (`History.client_ops` rule). Raises ValueError on
        malformed shapes WITHOUT mutating any encoder."""
        if not isinstance(unit_ops, (list, tuple)):
            raise ValueError("segment ops must be a list")
        if len(self.units) == 1 and (not unit_ops or
                                     isinstance(unit_ops[0], dict)):
            unit_ops = [unit_ops]
        if len(unit_ops) != len(self.units):
            raise ValueError(
                f"segment carries {len(unit_ops)} unit list(s); session "
                f"has {len(self.units)} unit(s)")
        parsed: List[List[Op]] = []
        for rows in unit_ops:
            out = []
            for d in rows:
                op = d if isinstance(d, Op) else Op.from_dict(dict(d))
                if isinstance(op.value, list):
                    op.value = tuple(op.value)
                if op.process != NEMESIS:
                    out.append(op)
            parsed.append(out)
        for unit, rows in zip(self.units, parsed):
            if not unit.decided and unit.enc is not None:
                unit.enc.validate(rows)
        return parsed

    # --------------------------------------------------------- appends

    def append(self, seq, unit_ops, n_bytes: int, journal=None,
               replaying: bool = False,
               digest: Optional[str] = None) -> dict:
        with self.lock:
            self.last_touch = time.monotonic()
            if self.status == INCOMPLETE:
                # the idle reaper parked this object between the
                # manager's lookup and our lock acquisition; the
                # manager revives and retries (units are freed here)
                raise _Parked()
            if self.status in (DONE, FAILED):
                raise StreamConflict(
                    f"session {self.sid} is {self.status}")
            if self.mode == "binary":
                raise StreamConflict(
                    f"session {self.sid} is on the binary lane; JSON "
                    "appends conflict", expected_seq=self.seq_next)
            try:
                seq = int(seq)
            except (TypeError, ValueError):
                raise ValueError(f"bad segment seq {seq!r}") from None
            # Replay passes the JOURNALED digest: the live path hashed
            # the wire payload as received, and a client retrying the
            # seq after a crash resends exactly that payload — the
            # idempotency check must compare like with like.
            if digest is None:
                digest = segment_digest(unit_ops)
            if seq in self.seen:
                if self.seen[seq] != digest:
                    raise StreamConflict(
                        f"segment {seq} was already appended with a "
                        f"different payload", expected_seq=self.seq_next)
                return dict(self._state(), duplicate=True)
            if seq != self.seq_next:
                raise StreamConflict(
                    f"out-of-order segment {seq} (expected "
                    f"{self.seq_next})", expected_seq=self.seq_next)
            if not replaying:
                wait = self._seg_bucket.take(1.0)
                if wait is None:
                    wait = self._byte_bucket.take(float(n_bytes))
                if wait is not None:
                    raise StreamBusy(
                        f"session {self.sid} over its segment budget",
                        retry_after_s=wait)
            parsed = self._parse_units(unit_ops)
            if journal is not None and not replaying:
                # Durability point: fsync'd before the 2xx — an
                # accepted segment survives SIGKILL from here on.
                journal.append_stream(encode_stream_segment(
                    self.sid, seq, [[op.to_dict() for op in rows]
                                    for rows in parsed], digest))
            self.mode = "json"
            self.seen[seq] = digest
            self.seq_next = seq + 1
            self.segments += 1
            self.bytes += int(n_bytes)
            for unit, rows in zip(self.units, parsed):
                unit.ingest(rows)
                self._advance(unit, seq)
            return self._state()

    def _parse_bseg_units(self, units_payload) -> List[dict]:
        """Binary twin of `_parse_units`: normalize/validate the
        per-unit suffix payloads (`frame.SegmentFrame` shape) WITHOUT
        mutating any unit — a malformed payload is a clean 400, never a
        half-ingested segment."""
        if not isinstance(units_payload, (list, tuple)):
            raise ValueError("binary segment units must be a list")
        if len(units_payload) != len(self.units):
            raise ValueError(
                f"segment carries {len(units_payload)} unit payload(s); "
                f"session has {len(self.units)} unit(s)")
        out: List[dict] = []
        for i, u in enumerate(units_payload):
            try:
                ev = np.ascontiguousarray(
                    u["events"], dtype=np.int32).reshape(-1, 5)
                n = int(ev.shape[0])
                oi = np.ascontiguousarray(
                    u["op_index"], dtype=np.int32).reshape(n)
                pr = u.get("proc")
                if pr is not None:
                    pr = np.ascontiguousarray(
                        pr, dtype=np.int32).reshape(n)
                out.append({
                    "events": ev, "op_index": oi, "proc": pr,
                    "n_slots": int(u["n_slots"]),
                    "n_ops": int(u["n_ops"]),
                    "consumed": int(u["consumed"]),
                    "final": bool(u.get("final", False)),
                })
            except (KeyError, TypeError, ValueError) as e:
                raise ValueError(
                    f"binary segment unit {i} malformed: {e}") from None
        return out

    def append_binary(self, seq, units_payload, n_bytes: int,
                      journal=None, replaying: bool = False,
                      digest: Optional[str] = None) -> dict:
        """Binary-lane append (ISSUE 18 tentpole (b)): the client's
        `IncrementalEncoder` already settled this suffix; the server
        ingests the arrays straight into the SAME decision ladder the
        JSON lane drives — greedy certifier, carried kernel, frozen
        verdicts — with no per-append encode. Sequencing, idempotency,
        flow control, and journal-before-2xx are the JSON append's
        rules verbatim; the journal record is a ``stream-bseg``
        (arrays, not op dicts) and replay feeds it back through this
        very method."""
        with self.lock:
            self.last_touch = time.monotonic()
            if self.status == INCOMPLETE:
                raise _Parked()
            if self.status in (DONE, FAILED):
                raise StreamConflict(
                    f"session {self.sid} is {self.status}")
            if self.mode == "json":
                raise StreamConflict(
                    f"session {self.sid} is on the JSON lane; binary "
                    "appends conflict", expected_seq=self.seq_next)
            try:
                seq = int(seq)
            except (TypeError, ValueError):
                raise ValueError(f"bad segment seq {seq!r}") from None
            if digest is None:
                digest = binary_segment_digest(units_payload)
            if seq in self.seen:
                if self.seen[seq] != digest:
                    raise StreamConflict(
                        f"segment {seq} was already appended with a "
                        f"different payload", expected_seq=self.seq_next)
                return dict(self._state(), duplicate=True)
            if seq != self.seq_next:
                raise StreamConflict(
                    f"out-of-order segment {seq} (expected "
                    f"{self.seq_next})", expected_seq=self.seq_next)
            if not replaying:
                wait = self._seg_bucket.take(1.0)
                if wait is None:
                    wait = self._byte_bucket.take(float(n_bytes))
                if wait is not None:
                    raise StreamBusy(
                        f"session {self.sid} over its segment budget",
                        retry_after_s=wait)
            parsed = self._parse_bseg_units(units_payload)
            if self.mode is None:
                # latch: swap every unit's encoder slot for the counter
                # mirror (mode None ⇒ zero accepted segments, so no
                # encoder state is lost)
                self.mode = "binary"
                for unit in self.units:
                    unit.enc = _BinaryEnc()
            if journal is not None and not replaying:
                # Durability point: fsync'd before the 2xx, same as the
                # JSON lane.
                journal.append_stream(encode_stream_bseg(
                    self.sid, seq, parsed, digest))
            self.seen[seq] = digest
            self.seq_next = seq + 1
            self.segments += 1
            self.bytes += int(n_bytes)
            for unit, u in zip(self.units, parsed):
                unit.ingest_encoded(u)
                self._advance(unit, seq)
            return self._state()

    def _advance(self, unit: StreamUnit, seq: int) -> None:
        """Run the decision ladder over a unit's newly settled events:
        greedy witness while it carries, then the carried kernel. A
        certain violation (frozen ``~ok ∧ ~overflow``) decides the unit
        HERE — at the earliest segment where it is decidable — and
        evicts it."""
        if unit.decided or unit.escalated or unit.enc is None:
            return
        self._maybe_spill(unit)
        if unit.greedy:
            if unit.spilled or unit.enc.n_events > greedy_max_events():
                self._drop_certifier(unit)
            else:
                # ISSUE 13/14: the value-guided bounded-backtrack
                # certifier, RESUMABLE — the carry (state, done-set,
                # pending, backtrack frame) persists between appends,
                # so this feed costs O(segment) where the PR-13
                # per-append restart re-scanned from op 0. Tier
                # namespaced @lin: a stream session certifies the
                # linearizable rung (fleet attribution must not
                # conflate it with the weak-rung certifier).
                if self._feed_certifier(unit):
                    unit.certified = True
                    unit.certify_tier = unit.certifier.tier + "@lin"
                    return
                self._drop_certifier(unit)
        # Kernel path: build/rebuild the carry, then drain the feed
        # queue. A window that outgrew the carry's slot bucket rebuilds
        # a wider carry and re-feeds the whole settled stream — the
        # rebuilt carry equals an uninterrupted wider scan
        # (deterministic; §14).
        if not self._ensure_scan(unit, final=False):
            return
        unit.drain_pending()
        if unit.scan is not None and unit.scan.decided:
            if unit.scan.overflow:
                # (False, True): the frontier overflowed its capacity —
                # invalid is no longer certain; full ladder at finish.
                unit.escalated = True
                return
            self._decide_invalid(unit, seq)

    def _feed_certifier(self, unit: StreamUnit) -> bool:
        """Drain the unit's settled-suffix queue into its resumable
        certifier (lazily built); True while the settled prefix stays
        certified."""
        from ..checker.consistency import StreamingCertifier

        if unit.certifier is None:
            unit.certifier = StreamingCertifier(self.model)
        ok = unit.certifier.certified
        for ev in unit.cert_queue:
            ok = unit.certifier.feed(ev)
            if not ok:
                break
        unit.cert_queue = []
        return ok

    def _drop_certifier(self, unit: StreamUnit) -> None:
        """The unit leaves the greedy path (spill, size cap, or an
        undecided certifier): free the certifier carry — the kernel
        carry takes over, and a dead certifier never un-decides."""
        unit.greedy = False
        unit.certified = False
        unit.certify_tier = None
        unit.certifier = None
        unit.cert_queue = []

    def _ensure_scan(self, unit: StreamUnit, final: bool) -> bool:
        """Build (or rebuild, when the window outgrew the slot bucket)
        the unit's carry and bring it current with the FULL settled
        stream — from the resident buffers, or from the WAL for a
        spilled unit (`final` settles outstanding invokes in the
        replay, matching a finish-time rebuild). False = the unit
        escalated (window beyond the kernel caps / WAL unavailable)."""
        if unit.scan is not None and unit.scan.fits(unit.enc.n_slots):
            return True
        try:
            unit.scan = CarriedScan(self.model, unit.enc.n_slots)
        except ValueError:
            # window beyond MAX_SLOTS: kernel-undecidable — the full
            # ladder (DFS fast path etc.) answers at finish
            unit.scan = None
            unit.escalated = True
            unit.pending = []
            return False
        if unit.spilled:
            # resident buffers are gone: rebuild the stream from the
            # WAL (deterministic — the same pipeline as a resume)
            if not self.manager._refeed_scan(self, unit, final=final):
                unit.scan = None
                unit.escalated = True
                unit.pending = []
                return False
        else:
            full = unit.settled_events()
            if full.shape[0]:
                unit.scan.feed(full)
        unit.pending = []   # covered by the full re-feed
        return True

    def _maybe_spill(self, unit: StreamUnit) -> None:
        if unit.spilled or unit.events_resident <= resident_events_cap():
            return
        if self.manager._journal is None:
            # no WAL to rebuild from: spilling would DESTROY the only
            # copy of the stream — keep the buffers and let memory
            # grow (the documented journaling-off trade).
            return
        # engage the kernel and bring it current BEFORE dropping the
        # buffers it would otherwise re-feed from
        if self._ensure_scan(unit, final=False):
            unit.drain_pending()
        self._drop_certifier(unit)
        unit.spilled = True
        unit._events = []
        unit._op_index = []
        unit._proc = []
        unit.events_resident = 0
        unit.ops = []

    def _invalid_result(self, unit: StreamUnit, seq: int) -> dict:  # requires(lock)
        """The certain-violation record (the frozen ``~ok ∧ ~overflow``
        pair), with a minimized counterexample when the op budget
        allows — ONE construction for the mid-run and finish paths."""
        from ..checker.schedule import note_tier

        note_tier("sort")
        res = {
            "valid?": INVALID,
            "algorithm": "jax-stream",
            "kernel": "sort-stream",
            "op-count": unit.enc.n_ops,
            "concurrency-window": unit.enc.n_slots,
            "decided-tier": "sort",
            "decided-at-segment": seq,
        }
        if unit.ops and unit.ops_total <= MAX_COUNTEREXAMPLE_OPS:
            try:
                from ..checker.counterexample import attach_counterexample

                attach_counterexample(res, History(list(unit.ops)),
                                      self.model)
            except Exception:
                LOG.warning("stream %s: counterexample attach failed",
                            self.sid, exc_info=True)
        if not self._replaying:
            self.manager._count("stream_violations")
        return res

    def _decide_invalid(self, unit: StreamUnit, seq: int) -> None:
        """A frozen violation mid-run: record the per-unit result,
        count it, and evict the row."""
        unit.result = self._invalid_result(unit, seq)
        unit.decided_seq = seq
        unit.free()

    # ---------------------------------------------------------- finish

    def finish(self, journal=None, replaying: bool = False) -> dict:
        with self.lock:
            self.last_touch = time.monotonic()
            if self.status == INCOMPLETE:
                raise _Parked()   # raced the reaper; manager revives
            if self.final is not None:
                return self.final   # idempotent
            if self.mode == "binary":
                # Soundness gate: the end-of-history settle ran on the
                # CLIENT. Without its final-flagged flush the crashed-
                # pair OPEN events of outstanding invokes never arrived
                # — and those are linearization candidates whose
                # absence can turn a valid history into a false
                # INVALID. Refuse rather than guess.
                missing = [i for i, u in enumerate(self.units)
                           if not u.decided and not u.bin_final]
                if missing:
                    raise StreamConflict(
                        f"binary session {self.sid}: unit(s) {missing} "
                        "have no final-flagged flush segment; append "
                        "one (final=true) before finish",
                        expected_seq=self.seq_next)
            results = []
            for unit in self.units:
                results.append(self._finish_unit(unit))
            valid = merge_valid(r.get("valid?") for r in results)
            self.final = {
                "session": self.sid,
                "status": DONE,
                "workload": self.workload,
                "algorithm": self.algorithm,
                "consistency": self.consistency,
                "valid?": valid,
                "results": results,
                "segments": self.segments,
                "resumed": self.resumed,
            }
            self.status = DONE
            if journal is not None and not replaying:
                journal.append_stream(encode_stream_fin(
                    self.sid, DONE, results=results))
            for unit in self.units:
                unit.free()
            return self.final

    def _finish_unit(self, unit: StreamUnit) -> dict:  # requires(lock)
        if unit.decided:
            return unit.result
        # flush: outstanding invokes become crashed pairs (pair_ops'
        # end-of-history rule) and every remaining event settles. On
        # the binary lane the CLIENT ran this flush (finish() enforced
        # that its final-flagged segment arrived).
        if unit.enc is not None and self.mode != "binary":
            unit.ingest([], final=True)
        if unit.greedy and not unit.spilled \
                and unit.enc.n_events <= greedy_max_events():
            from ..checker.schedule import note_tier

            # The resumable certifier consumes only the final settle
            # suffix here (ISSUE 14) — the earlier segments' witness
            # is already in its carry.
            if self._feed_certifier(unit):
                tier = unit.certifier.tier + "@lin"
                unit.certified = True
                unit.certify_tier = tier
                note_tier(tier)
                return {"valid?": VALID, "algorithm": "greedy-witness",
                        "op-count": unit.enc.n_ops,
                        "concurrency-window": unit.enc.n_slots,
                        "decided-tier": tier}
        self._drop_certifier(unit)
        if not unit.escalated:
            # final=True: a spilled unit's WAL rebuild must apply the
            # same end-of-history settle the live encoder just did —
            # outstanding invokes become crashed pairs, and their OPEN
            # events are linearization candidates the verdict needs.
            if self._ensure_scan(unit, final=True):
                unit.drain_pending()
            if not unit.escalated and unit.scan is not None:
                if unit.scan.ok:
                    from ..checker.schedule import note_tier

                    note_tier("sort")
                    return {"valid?": VALID, "algorithm": "jax-stream",
                            "kernel": "sort-stream",
                            "op-count": unit.enc.n_ops,
                            "concurrency-window": unit.enc.n_slots,
                            "decided-tier": "sort"}
                if not unit.scan.overflow:
                    return self._invalid_result(unit, self.segments)
                unit.escalated = True
        # Escalation: the carried sort kernel could not certify
        # (overflow / window beyond its caps) — run the full ladder on
        # the complete history through the STANDARD encode (prune ON:
        # dead-crashed-op pruning is exactly what tames the wide
        # windows that land here), so the escalated verdict is the
        # one-shot `check_histories` verdict by construction. Raw ops
        # come from the resident buffer or the WAL; the unpruned
        # settled stream is the (sound) last resort.
        from ..checker.linearizable import check_encoded
        from ..history.packing import encode_history

        ops = (list(unit.ops) if unit.ops
               and len(unit.ops) == unit.ops_total else None)
        if ops is None and self.mode != "binary":
            ops = self.manager._replay_ops(self, unit)
        if ops is not None:
            enc = encode_history(ops, self.model)
        elif not unit.spilled:
            # binary lane lands here by construction (no raw ops exist
            # server-side): the settled stream IS the complete encoding
            # — the client's final flush settled every event.
            enc = unit.settled_encoding()
        else:
            enc = (self.manager._journaled_binary_encoding(self, unit)
                   if self.mode == "binary" else None)
        if enc is None:
            return {"valid?": None, "algorithm": "stream",
                    "error": "stream not reconstructable from journal"}
        [res] = check_encoded([enc], self.model,
                              algorithm=self.algorithm)
        res["escalated-from-stream"] = True
        return res

    # ---------------------------------------------------------- status

    def _unit_state(self, i: int, unit: StreamUnit) -> dict:
        d = {"unit": i, "ops": unit.ops_total if unit.enc is None
             else unit.enc.consumed}
        if unit.decided:
            d["status"] = "invalid"
            d["decided-at-segment"] = unit.decided_seq
            d["result"] = unit.result
        elif unit.escalated:
            d["status"] = "escalated"
        elif unit.greedy:
            d["status"] = "certified" if unit.certified else "streaming"
            if unit.certified and unit.certify_tier:
                d["decided-tier"] = unit.certify_tier
        else:
            d["status"] = "streaming"
            if unit.scan is not None:
                d["events-scanned"] = unit.scan.fed
        return d

    def _state(self) -> dict:  # requires(lock)
        violations = [self._unit_state(i, u)
                      for i, u in enumerate(self.units) if u.decided]
        d = {
            "session": self.sid,
            "status": self.status,
            "workload": self.workload,
            "units": len(self.units),
            "next_seq": self.seq_next,
            "segments": self.segments,
            "resumed": self.resumed,
            "unit_states": [self._unit_state(i, u)
                            for i, u in enumerate(self.units)],
        }
        if self.mode is not None:
            d["mode"] = self.mode
        if violations:
            d["violation"] = violations[0]
            d["valid?"] = INVALID
        if self.final is not None:
            d.update(self.final)
        if self.error:
            d["error"] = self.error
        return d

    def state(self) -> dict:
        # Deliberately NOT an idle touch: a monitor polling
        # /stream/status must not keep an abandoned producer's session
        # resident forever — only appends/finish reset the idle clock.
        with self.lock:
            return self._state()

    def park(self) -> None:
        """Finalize-as-incomplete: free every unit's memory; the
        session remains resumable from its journaled segments."""
        with self.lock:
            if self.status != OPEN:
                return
            self.status = INCOMPLETE
            for unit in self.units:
                unit.free()


class _Stub:
    """A parked/restored session: journal-backed, nearly free in
    memory. `status` is INCOMPLETE (resumable) or a terminal state
    restored from a fin record."""

    def __init__(self, sid: str, status: str = INCOMPLETE,
                 final: Optional[dict] = None):
        self.sid = sid
        self.status = status
        self.final = final

    def state(self) -> dict:
        d = {"session": self.sid, "status": self.status,
             "resumable": self.status == INCOMPLETE}
        if self.final is not None:
            d.update(self.final)
        return d


class StreamManager:
    """Owns every stream session of one daemon: admission caps, the
    idle reaper, journal/replay wiring, and the handoff surface the
    cluster tier calls."""

    def __init__(self, service):
        self.service = service
        self._sessions: dict = {}  # guarded_by(_lock)
        self._lock = threading.Lock()
        self._stats = {  # guarded_by(_lock)
            "stream_sessions": 0,      # opened (lifetime)
            "segments_total": 0,
            "resumed_sessions": 0,
            "binary_segments": 0,
            "stream_violations": 0,
            "stream_rejected": 0,
            "stream_idle_parked": 0,
            "handoff_streams": 0,
        }
        self._peak_rows = 0  # guarded_by(_lock)
        self._stop = threading.Event()
        self._reaper: Optional[threading.Thread] = None

    @property
    def _journal(self):
        return self.service._journal

    def _count(self, *keys: str) -> None:
        with self._lock:
            for k in keys:
                self._stats[k] = self._stats.get(k, 0) + 1

    # ------------------------------------------------------- lifecycle

    def ensure_reaper(self) -> None:
        if idle_timeout_s() <= 0:
            return
        with self._lock:
            if self._reaper is None or not self._reaper.is_alive():
                self._stop.clear()
                self._reaper = threading.Thread(
                    target=self._reaper_loop, daemon=True,
                    name="graftd-stream-reaper")
                self._reaper.start()

    def _reaper_loop(self) -> None:
        idle = idle_timeout_s()
        poll = max(0.05, min(idle / 4.0, 5.0))
        while not self._stop.wait(poll):
            now = time.monotonic()
            with self._lock:
                # liveness snapshot: a stale OPEN only means one extra
                # poll — every mutation below re-checks under s.lock
                live = [s for s in self._sessions.values()
                        if isinstance(s, StreamSession)
                        and s.status == OPEN]  # lint: allow(unguarded)
            for s in live:
                if now - s.last_touch <= idle:
                    continue
                if self._journal is not None:
                    s.park()
                    with self._lock:
                        self._sessions[s.sid] = _Stub(s.sid)
                    self._count("stream_idle_parked")
                    LOG.warning("stream %s idle >%gs; parked as "
                                "incomplete (resumable)", s.sid, idle)
                else:
                    # no journal: nothing to resume from — fail loudly
                    with s.lock:
                        if s.status == OPEN:
                            s.status = FAILED
                            s.error = (f"idle past {idle:g}s with no "
                                       "journal to resume from")
                            for u in s.units:
                                u.free()
                    LOG.warning("stream %s idle >%gs with journaling "
                                "off; session failed", s.sid, idle)

    def shutdown(self) -> None:
        self._stop.set()
        t = self._reaper
        if t is not None and t.is_alive():
            t.join(5.0)

    # ------------------------------------------------------- admission

    def open(self, workload: str = "register", units: int = 1,
             algorithm: str = "auto", consistency: str = "linearizable",
             session_id: Optional[str] = None,
             resume: bool = False) -> dict:
        from .request import service_workloads

        if resume and session_id:
            return self._touch(str(session_id)).state()
        consistency = str(consistency or "linearizable")
        if consistency != "linearizable":
            raise ValueError(
                "streaming sessions serve the linearizable rung only "
                "(weaker rungs relax FORCE placement along per-process "
                "future order, which is not prefix-stable); submit the "
                "finished history with consistency="
                f"{consistency!r} instead")
        workloads = service_workloads()
        if workload not in workloads:
            raise ValueError(f"unknown workload {workload!r} "
                             f"(have: {', '.join(sorted(workloads))})")
        model_factory, independent = workloads[workload]
        if independent:
            raise ValueError(
                f"workload {workload!r} splits per key at admission; "
                "stream each key as its own unit instead")
        units = int(units)
        if not 1 <= units <= 256:
            raise ValueError(f"units must be in [1, 256] (got {units})")
        sid = str(session_id) if session_id else uuid.uuid4().hex[:12]
        with self._lock:
            if sid in self._sessions:
                raise StreamConflict(f"session {sid} already exists "
                                     "(pass resume=true to re-attach)")
            # admission-cap snapshot: a concurrently-finishing session
            # can only make the count pessimistic (429 + retry-after)
            live = sum(1 for s in self._sessions.values()
                       if isinstance(s, StreamSession)
                       and s.status == OPEN)  # lint: allow(unguarded)
            if live >= sessions_cap():
                self._stats["stream_rejected"] += 1
                raise StreamBusy(
                    f"{live} live sessions (JGRAFT_STREAM_SESSIONS)",
                    retry_after_s=max(idle_timeout_s() / 8.0, 1.0))
            sess = StreamSession(self, sid, workload, model_factory(),
                                 str(algorithm), consistency, units)
            self._sessions[sid] = sess
            self._stats["stream_sessions"] += 1
        if self._journal is not None:
            self._journal.append_stream(encode_stream_open(
                sid, workload, type(sess.model).__name__,
                str(algorithm), consistency, units))
        self.ensure_reaper()
        return sess.state()

    def _get(self, sid: str):
        with self._lock:
            s = self._sessions.get(sid)
        if s is None:
            raise KeyError(sid)
        return s

    def _touch(self, sid: str) -> StreamSession:
        """Session for a mutating call, reviving a parked stub from the
        WAL (the resume path — also how a restarted daemon serves the
        first post-crash append)."""
        s = self._get(sid)
        if isinstance(s, StreamSession):
            return s
        # s is a parked _Stub here (the isinstance return above filtered
        # live sessions): stubs are frozen at park/fin time, no lock
        if s.status != INCOMPLETE:  # lint: allow(unguarded)
            raise StreamConflict(f"session {sid} is {s.status}")  # lint: allow(unguarded)
        return self._revive(sid)

    # --------------------------------------------------------- surface

    def append(self, sid: str, seq, unit_ops, n_bytes: int) -> dict:
        sid = str(sid)
        for _attempt in range(2):
            sess = self._touch(sid)
            try:
                out = sess.append(seq, unit_ops, n_bytes,
                                  journal=self._journal)
                break
            except _Parked:
                # lost the race with the idle reaper: the manager map
                # already holds the resumable stub — retry revives it
                continue
        else:
            raise StreamConflict(f"session {sid} is parked")
        self._count("segments_total")
        self._note_rows()
        return out

    def append_binary(self, sid: str, seq, units_payload, n_bytes: int,
                      digest: Optional[str] = None) -> dict:
        """Binary-lane append surface (ISSUE 18): same park-race retry
        as `append`. `digest` is the HTTP layer's hash of the raw frame
        bytes (None → content hash of the arrays)."""
        sid = str(sid)
        for _attempt in range(2):
            sess = self._touch(sid)
            try:
                out = sess.append_binary(seq, units_payload, n_bytes,
                                         journal=self._journal,
                                         digest=digest)
                break
            except _Parked:
                continue
        else:
            raise StreamConflict(f"session {sid} is parked")
        self._count("segments_total", "binary_segments")
        self._note_rows()
        return out

    def status(self, sid: str) -> dict:
        return self._get(str(sid)).state()

    def finish(self, sid: str) -> dict:
        sid = str(sid)
        with self._lock:
            s = self._sessions.get(sid)
        # frozen _Stub again: park/fin wrote its status once, pre-publish
        if isinstance(s, _Stub) and s.status not in (INCOMPLETE,):  # lint: allow(unguarded)
            # finish is idempotent ACROSS restarts too: a retried
            # finish whose first 2xx was lost must read the fin-record
            # stub's final state, not a 409.
            return s.state()
        for _attempt in range(2):
            sess = self._touch(sid)
            try:
                out = sess.finish(journal=self._journal)
                break
            except _Parked:
                continue
        else:
            raise StreamConflict(f"session {sid} is parked")
        self._note_rows()
        return out

    def _note_rows(self) -> None:
        with self._lock:
            # metrics snapshot (peak-resident gauge): drift is noise,
            # not a correctness hazard
            rows = sum(sum(1 for u in s.units if not u.decided)
                       for s in self._sessions.values()
                       if isinstance(s, StreamSession)
                       and s.status == OPEN)  # lint: allow(unguarded)
            self._peak_rows = max(self._peak_rows, rows)

    # ---------------------------------------------------------- replay

    def restore(self, streams: dict) -> None:
        """Boot-time restore from `journal.replay()["streams"]`:
        finished sessions become terminal stubs (status queryable),
        unfinished ones parked resumable stubs — the first touch
        replays their segments (lazy: boot stays fast no matter how
        many sessions the WAL holds)."""
        for sid, s in streams.items():
            fin = s.get("fin")
            with self._lock:
                if sid in self._sessions:
                    continue
                if fin is not None:
                    final = {k: fin[k] for k in
                             ("status", "results", "error")
                             if k in fin}
                    if "results" in final:
                        final["valid?"] = merge_valid(
                            r.get("valid?") for r in final["results"])
                    self._sessions[sid] = _Stub(
                        sid, status=fin.get("status", DONE), final=final)
                else:
                    self._sessions[sid] = _Stub(sid)

    def _revive(self, sid: str) -> StreamSession:
        """Rebuild a parked session by replaying its journaled records
        through the live pipeline — deterministic, so the revived
        carry/verdict state is bitwise-identical to the uninterrupted
        session's (§14)."""
        if self._journal is None:
            raise StreamConflict(
                f"session {sid} is parked and journaling is off")
        recs = self._journal.stream_records(sid)
        if recs is None:
            raise StreamConflict(
                f"session {sid} has no intact journal records")
        sess = self._build_from_records(sid, recs)
        sess.resumed = True
        with self._lock:
            self._sessions[sid] = sess
            self._stats["resumed_sessions"] += 1
        self._note_rows()
        return sess

    def _build_from_records(self, sid: str, recs: dict) -> StreamSession:
        from .request import service_workloads

        op = recs["open"]
        workloads = service_workloads()
        wl = op.get("workload")
        if wl not in workloads:
            raise StreamConflict(f"session {sid}: unknown workload "
                                 f"{wl!r} in journal")
        model_factory, _ = workloads[wl]
        sess = StreamSession(self, sid, wl, model_factory(),
                             str(op.get("algorithm", "auto")),
                             str(op.get("consistency", "linearizable")),
                             int(op.get("units", 1)))
        sess._replaying = True
        try:
            for seg in recs["segments"]:
                try:
                    if seg.get("kind") == "stream-bseg":
                        sess.append_binary(
                            seg["seq"], decode_stream_bseg_units(seg),
                            n_bytes=0, replaying=True,
                            digest=seg.get("digest"))
                    else:
                        sess.append(seg["seq"], seg["ops"], n_bytes=0,
                                    replaying=True,
                                    digest=seg.get("digest"))
                except (ValueError, KeyError, StreamConflict) as e:
                    # deterministic re-raise of a rejected segment: the
                    # live path already answered the client; skip loudly
                    LOG.warning("stream %s: journaled segment %s "
                                "rejected on replay: %s", sid,
                                seg.get("seq"), e)
        finally:
            sess._replaying = False
        return sess

    def _journaled_unit_ops(self, sess: StreamSession,
                            unit: StreamUnit) -> Optional[list]:
        """Per-segment raw Op rows of ONE unit, re-read from the WAL —
        the single home of the journal-payload normalization (the
        flat-vs-nested rule and list→tuple value retupling). Returns
        [[Op…] per segment] or None when the WAL cannot answer."""
        if self._journal is None:
            return None
        recs = self._journal.stream_records(sess.sid)
        if recs is None:
            return None
        idx = sess.units.index(unit)
        out = []
        for seg in recs["segments"]:
            if seg.get("kind") == "stream-bseg":
                return None   # binary lane: no raw ops exist in the WAL
            rows = seg["ops"]
            if len(sess.units) == 1 and (not rows or
                                         isinstance(rows[0], dict)):
                rows = [rows]
            ops = []
            for d in rows[idx]:
                op = Op.from_dict(dict(d))
                if isinstance(op.value, list):
                    op.value = tuple(op.value)
                ops.append(op)
            out.append(ops)
        return out

    def _journaled_binary_units(self, sess: StreamSession,
                                unit: StreamUnit) -> Optional[list]:
        """Per-segment binary payload dicts of ONE unit, re-read from
        the WAL's ``stream-bseg`` records (the binary lane's twin of
        `_journaled_unit_ops`). None when the WAL cannot answer."""
        if self._journal is None:
            return None
        recs = self._journal.stream_records(sess.sid)
        if recs is None:
            return None
        idx = sess.units.index(unit)
        out = []
        for seg in recs["segments"]:
            if seg.get("kind") != "stream-bseg":
                return None
            try:
                units = decode_stream_bseg_units(seg)
                out.append(units[idx])
            except (KeyError, IndexError, TypeError, ValueError):
                LOG.warning("stream %s: journaled binary segment %s "
                            "undecodable", sess.sid, seg.get("seq"),
                            exc_info=True)
                return None
        return out

    def _journaled_binary_encoding(
            self, sess: StreamSession,
            unit: StreamUnit) -> Optional[EncodedHistory]:
        """A spilled binary unit's COMPLETE settled encoding rebuilt
        from the WAL (finish-escalation path: the resident buffers are
        gone and no raw ops ever existed server-side)."""
        segs = self._journaled_binary_units(sess, unit)
        if segs is None:
            return None
        ev = (np.concatenate([u["events"] for u in segs])
              if segs else np.zeros((0, 5), np.int32))
        oi = (np.concatenate([u["op_index"] for u in segs])
              if segs else np.zeros((0,), np.int32))
        pr = (np.concatenate(
                  [u["proc"] if u["proc"] is not None
                   else np.zeros(u["events"].shape[0], np.int32)
                   for u in segs])
              if segs else np.zeros((0,), np.int32))
        return EncodedHistory(
            events=np.ascontiguousarray(ev, dtype=np.int32),
            op_index=oi,
            n_slots=max((u["n_slots"] for u in segs), default=0),
            n_ops=max((u["n_ops"] for u in segs), default=0),
            proc=pr)

    def _refeed_scan(self, sess: StreamSession, unit: StreamUnit,
                     final: bool = False) -> bool:
        """Rebuild a SPILLED unit's carry from the WAL: replay the
        session's segments through a scratch encoder and feed the full
        settled stream into the (fresh) carry. ``final`` applies the
        end-of-history settle too (a finish-time rebuild must see the
        crashed-pair OPENs of outstanding invokes, exactly like the
        live encoder's final flush). True on success.

        Binary lane: the journaled arrays ARE the settled stream — no
        scratch encoder runs, and ``final`` needs no extra settle
        because the client's final flush is itself a journaled segment
        (finish() refuses to run without it)."""
        # mode read under the session lock held by the _ensure_scan
        # caller (RLock; mode is also latched by the time a unit spills)
        if sess.mode == "binary":  # lint: allow(unguarded)
            bsegs = self._journaled_binary_units(sess, unit)
            if bsegs is None:
                return False
            for u in bsegs:
                ev = u["events"]
                if ev.shape[0] and unit.scan is not None:
                    unit.scan.feed(ev)
                    if unit.scan.decided:
                        return True
            return True
        segments = self._journaled_unit_ops(sess, unit)
        if segments is None:
            return False
        enc = IncrementalEncoder(sess.model)
        for rows in segments:
            ev, _oi, _pr = enc.feed(rows)
            if ev.shape[0] and unit.scan is not None:
                unit.scan.feed(ev)
                if unit.scan.decided:
                    return True
        if final:
            ev, _oi, _pr = enc.feed([], final=True)
            if ev.shape[0] and unit.scan is not None:
                unit.scan.feed(ev)
        return True

    def _replay_ops(self, sess: StreamSession,
                    unit: StreamUnit) -> Optional[list]:
        """A unit's complete raw op rows reconstructed from the WAL
        (finish-escalation of a spilled/over-budget unit)."""
        segments = self._journaled_unit_ops(sess, unit)
        if segments is None:
            return None
        return [op for rows in segments for op in rows]

    # --------------------------------------------------------- cluster

    def adopt(self, streams: dict, origin: str = "") -> int:
        """Re-own a dead replica's stream sessions (the PR 11 handoff,
        stream flavor): every record is re-journaled under THIS
        replica's WAL before the session becomes visible — the same
        no-gap durability chain as `adopt_requests` — then unfinished
        sessions appear as parked resumable stubs and finished ones as
        terminal stubs. Returns sessions taken (the manager keeps the
        claimed dir when the take was partial)."""
        taken = 0
        for sid, s in streams.items():
            if self.service._stop.is_set():
                break
            with self._lock:
                if sid in self._sessions:
                    taken += 1   # already known (idempotent re-adopt)
                    continue
            if self._journal is not None:
                if s.get("open") is not None:
                    self._journal.append_stream(dict(s["open"]))
                for seg in s.get("segments", ()):
                    self._journal.append_stream(dict(seg))
                if s.get("fin") is not None:
                    self._journal.append_stream(dict(s["fin"]))
            self.restore({sid: s})
            self._count("handoff_streams")
            taken += 1
        if taken:
            LOG.warning("adopted %d stream session(s) from expired "
                        "replica %s", taken, origin or "<unknown>")
        return taken

    # ----------------------------------------------------------- stats

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._stats)
            out["stream_live_sessions"] = sum(
                1 for s in self._sessions.values()
                # /stats gauge: racy read is the documented contract
                if isinstance(s, StreamSession)
                and s.status == OPEN)  # lint: allow(unguarded)
            out["peak_resident_rows"] = self._peak_rows
        return out

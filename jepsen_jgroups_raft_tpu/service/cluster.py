"""Replica membership, liveness leases, and cross-replica journal
handoff: graftd's cluster tier (ISSUE 11 tentpoles (b) and (c)).

A cluster is N daemons sharing one directory (the cluster dir): the
content-addressed result store (service/store.py), a ``leases/`` dir of
liveness leases, and a ``journal/<replica>/`` WAL per replica. There is
deliberately NO coordinator process — every cluster-wide decision is a
pure function of the shared filesystem, made atomic by the two
primitives the journal and store already lean on (``os.replace`` for
publish, ``os.rename`` for claim):

* **Leases.** Each replica heartbeats a lease file carrying its url,
  load (queue depth / retry-after estimate), and a wall-clock renewal
  stamp. A lease is expired only when ``now > renewed + ttl + skew``:
  the skew margin (``JGRAFT_CLUSTER_SKEW_S``) tolerates the wall-clock
  disagreement real fleets have, and a lease stamped in the FUTURE by a
  fast-clock replica is simply alive — expiry is one-sided, so skew can
  delay a handoff but never trigger a false one against a live replica.
  Corrupt lease files are skipped loudly (a torn heartbeat must not
  eject a replica).
* **Load shedding.** A replica past its shed threshold
  (``JGRAFT_SERVICE_SHED_DEPTH``; 0 = capacity-only, today's behavior)
  rejects with 429 carrying the CLUSTER'S best retry-after — the
  minimum over live leases — not its own: the client's backoff+failover
  then lands on the least-loaded replica instead of camping on the
  loaded one.
* **Journal handoff.** A replica whose lease expires leaves a WAL of
  accepted-but-unfinished work. A surviving replica CLAIMS it by
  atomically renaming ``journal/<dead>`` to
  ``journal/<dead>.claim.<survivor>`` — rename succeeds for exactly one
  claimant, which is the whole no-double-ownership argument — then
  replays it through the existing journal machinery: finished clean
  verdicts are lifted into the shared store, unfinished entries are
  re-admitted (re-journaled under the claimant's OWN lease first, so
  the durability chain never has a gap), and the claimed dir plus the
  dead lease are removed. A claimant that itself dies mid-adoption
  leaves the ``.claim.`` dir behind; the scan treats a claim dir whose
  claimant's lease is expired as claimable again, so *accepted ⇒
  eventually checked* holds as long as any replica survives.

Everything here is INERT unless a cluster dir is configured — the
single-replica daemon constructs no ClusterManager and touches none of
these files.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import threading
import time
from pathlib import Path
from typing import List, Optional

from ..platform import env_float
from .store import ResultStore

LOG = logging.getLogger("jgraft.service")

#: Lease schema version (reads skip newer-versioned leases loudly).
LEASE_VERSION = 1

#: Marker separating a claimed journal dir's origin from its claimant.
CLAIM_SEP = ".claim."


def lease_ttl_s() -> float:
    """Lease time-to-live (JGRAFT_CLUSTER_TTL_S, default 10 s): how
    stale a heartbeat may be before peers treat the replica as dead and
    hand off its journal. Defensively parsed like every env gate."""
    return env_float("JGRAFT_CLUSTER_TTL_S", 10.0, minimum=0.05)


def skew_tolerance_s() -> float:
    """Extra slack past the TTL before a lease counts as expired
    (JGRAFT_CLUSTER_SKEW_S, default 2 s) — the wall-clock disagreement
    budget between replicas writing and reading lease stamps."""
    return env_float("JGRAFT_CLUSTER_SKEW_S", 2.0, minimum=0.0)


def shed_depth() -> int:
    """Queue depth at which a replica starts shedding to the cluster
    (JGRAFT_SERVICE_SHED_DEPTH; default 0 disables early shedding —
    only queue capacity rejects, today's single-replica behavior)."""
    from ..platform import env_int

    return env_int("JGRAFT_SERVICE_SHED_DEPTH", 0, minimum=0)


def _lease_crc(rec: dict) -> str:
    from .journal import _crc_line

    return _crc_line(rec)


def read_lease(path) -> Optional[dict]:
    """Parse one lease file; corrupt/torn/newer-versioned leases are
    skipped LOUDLY (a mangled heartbeat must never eject a replica or
    crash a reader) and report as None."""
    path = Path(path)
    try:
        raw = path.read_bytes()
    except FileNotFoundError:
        return None
    except OSError:
        LOG.warning("cluster: lease read of %s failed", path,
                    exc_info=True)
        return None
    try:
        rec = json.loads(raw)
        if not isinstance(rec, dict):
            raise ValueError("lease is not an object")
        if int(rec.get("v", -1)) > LEASE_VERSION:
            raise ValueError(f"lease version {rec.get('v')} is newer "
                             f"than this replica ({LEASE_VERSION})")
        if rec.get("crc") != _lease_crc(rec):
            raise ValueError("crc mismatch (torn lease write)")
        float(rec["renewed_wall"])
        float(rec["ttl_s"])
    except (ValueError, KeyError, TypeError, json.JSONDecodeError) as e:
        LOG.warning("cluster: corrupt lease %s skipped: %s", path, e)
        return None
    return rec


def lease_expired(lease: dict, now: Optional[float] = None,
                  skew_s: Optional[float] = None) -> bool:
    """One-sided expiry (module docstring): stale beyond ttl+skew is
    dead; a future-dated stamp (fast writer clock) is alive."""
    now = time.time() if now is None else now
    skew = skew_tolerance_s() if skew_s is None else skew_s
    return now > float(lease["renewed_wall"]) + float(lease["ttl_s"]) + skew


def live_replicas(root, skew_s: Optional[float] = None) -> List[dict]:
    """Non-expired leases under <root>/leases, sorted by replica id
    (deterministic for tests and routing)."""
    leases_dir = Path(root) / "leases"
    out: List[dict] = []
    try:
        paths = sorted(leases_dir.glob("*.json"))
    except OSError:
        return out
    now = time.time()
    for p in paths:
        lease = read_lease(p)
        if lease is not None and not lease_expired(lease, now=now,
                                                   skew_s=skew_s):
            out.append(lease)
    return out


def discover_replica_urls(root) -> List[str]:
    """Advertised URLs of the live replicas (clients bootstrap their
    replica list from this when they share the cluster filesystem)."""
    return [lease["url"] for lease in live_replicas(root)
            if lease.get("url")]


class ClusterManager:
    """One replica's membership + handoff agent (owned by its
    CheckingService). Runs a single daemon thread that heartbeats the
    lease every ttl/3 and scans for expired peers every
    `scan_interval_s`; `shutdown()` removes the lease (a clean exit has
    no unfinished WAL entries — shutdown fails queued work loudly with
    terminal markers — so there is nothing to hand off)."""

    def __init__(self, service, root, replica_id: str,
                 url: Optional[str] = None,
                 lease_ttl: Optional[float] = None,
                 scan_interval_s: Optional[float] = None,
                 skew_s: Optional[float] = None,
                 autostart: bool = True):
        self.service = service
        self.root = Path(root)
        self.replica_id = str(replica_id)
        self.url = url
        self.store = ResultStore(self.root)
        self.lease_ttl = (lease_ttl if lease_ttl is not None
                          else lease_ttl_s())
        self.skew_s = skew_s if skew_s is not None else skew_tolerance_s()
        self.scan_interval_s = (scan_interval_s if scan_interval_s
                                is not None
                                else max(self.lease_ttl / 2.0, 0.2))
        self.shed_depth = shed_depth()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        try:
            (self.root / "leases").mkdir(parents=True, exist_ok=True)
            (self.root / "journal").mkdir(parents=True, exist_ok=True)
        except OSError:
            LOG.warning("cluster: layout mkdir under %s failed",
                        self.root, exc_info=True)
        # First lease BEFORE the service replays its own journal (the
        # daemon constructs the manager before _recover): a restarting
        # replica re-arms its liveness before peers can mistake the
        # boot-time replay window for death and claim the WAL it is
        # replaying.
        self.renew_lease()
        if autostart:
            self.start()

    # ------------------------------------------------------- lifecycle

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        # Publish liveness BEFORE the heartbeat thread exists: a
        # shutdown()/start() cycle removed the lease, and the loop's
        # first renewal is a whole beat away — in that window a peer's
        # scan would find no lease at all (no ttl+skew grace applies to
        # a missing file) and claim the LIVE WAL this replica is about
        # to append to.
        self.renew_lease()
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"{self.replica_id}-cluster")
        self._thread.start()

    def shutdown(self, timeout: float = 10.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout)
        try:
            self._lease_path(self.replica_id).unlink(missing_ok=True)
        except OSError:
            LOG.warning("cluster: lease removal failed on shutdown",
                        exc_info=True)

    def _loop(self) -> None:
        beat = max(self.lease_ttl / 3.0, 0.05)
        next_scan = time.monotonic() + self.scan_interval_s
        while not self._stop.wait(beat):
            try:
                self.renew_lease()
                if time.monotonic() >= next_scan:
                    next_scan = time.monotonic() + self.scan_interval_s
                    self.handoff_scan()
            except Exception:  # noqa: BLE001 — the heartbeat must
                # survive any one iteration's failure: a dead heartbeat
                # expires the lease and peers would steal a LIVE
                # replica's journal. Logged loudly, never swallowed.
                LOG.exception("cluster: heartbeat/handoff iteration "
                              "failed on %s", self.replica_id)

    # ---------------------------------------------------------- leases

    def _lease_path(self, replica_id: str) -> Path:
        return self.root / "leases" / f"{replica_id}.json"

    def set_url(self, url: str) -> None:
        """Late-bind the advertised URL (the HTTP front knows its bound
        port only after the service exists) and re-publish the lease."""
        self.url = url
        self.renew_lease()

    def renew_lease(self) -> None:
        """Publish this replica's liveness + load advertisement
        atomically (temp + `os.replace` — a reader never sees a torn
        lease, only the previous whole one)."""
        svc = self.service
        rec = {
            "v": LEASE_VERSION,
            "replica": self.replica_id,
            "url": self.url,
            "pid": os.getpid(),
            "renewed_wall": time.time(),
            "ttl_s": self.lease_ttl,
            "queue_depth": svc.queue.depth,
            "queue_capacity": svc.queue.capacity,
            "retry_after_s": svc._retry_after(),
        }
        rec["crc"] = _lease_crc(rec)
        path = self._lease_path(self.replica_id)
        tmp = path.with_name(f".{self.replica_id}.{os.getpid()}.tmp")
        try:
            path.parent.mkdir(parents=True, exist_ok=True)
            with open(tmp, "w") as fh:
                json.dump(rec, fh, sort_keys=True, separators=(",", ":"))
            os.replace(tmp, path)
        except OSError:
            LOG.warning("cluster: lease renewal failed for %s",
                        self.replica_id, exc_info=True)

    def peers(self) -> List[dict]:
        """Live leases excluding this replica's own."""
        return [lease for lease in live_replicas(self.root,
                                                 skew_s=self.skew_s)
                if lease.get("replica") != self.replica_id]

    def best_retry_after(self, own_retry_after_s: float) -> float:
        """The cluster's best backpressure hint (tentpole (b)): the
        minimum retry-after over live replicas — a shedding replica's
        429 tells the client when the LEAST-loaded peer frees a slot,
        so the jittered-backoff retry lands where there is room."""
        best = float(own_retry_after_s)
        for lease in self.peers():
            try:
                # a peer already at capacity is not a better target no
                # matter what its estimate says
                if int(lease.get("queue_depth", 0)) >= \
                        int(lease.get("queue_capacity", 1)):
                    continue
                best = min(best, float(lease["retry_after_s"]))
            except (ValueError, KeyError, TypeError):
                continue  # malformed advert: lease CRC passed but a
                # field is off-schema; skip just this peer
        return round(max(0.1, best), 2)

    def should_shed(self) -> bool:
        """Past the shed threshold (0 = disabled)? The daemon checks
        this before queue insert and answers 429 with
        `best_retry_after` when true."""
        return 0 < self.shed_depth <= self.service.queue.depth

    # --------------------------------------------------------- handoff

    def _journal_root(self) -> Path:
        return self.root / "journal"

    def journal_dir(self) -> Path:
        """This replica's own WAL directory inside the shared layout."""
        return self._journal_root() / self.replica_id

    def _lease_alive(self, replica_id: str) -> bool:
        lease = read_lease(self._lease_path(replica_id))
        return lease is not None and not lease_expired(
            lease, skew_s=self.skew_s)

    def handoff_scan(self) -> int:
        """One pass of the cross-replica handoff (tentpole (c)): claim
        and adopt every journal dir whose owner's lease is expired.
        Returns the number of dirs adopted this pass."""
        adopted = 0
        try:
            entries = sorted(self._journal_root().iterdir())
        except OSError:
            return adopted
        for entry in entries:
            if not entry.is_dir():
                continue
            name = entry.name
            origin = name.split(CLAIM_SEP, 1)[0]
            if CLAIM_SEP in name:
                claimant = name.rsplit(CLAIM_SEP, 1)[1]
                if claimant == self.replica_id:
                    # our own stale claim (we crashed mid-adoption and
                    # restarted): resume it — the rename already made
                    # it exclusively ours
                    adopted += self._adopt(entry, origin)
                    continue
                if self._lease_alive(claimant):
                    continue  # someone live owns this handoff
            else:
                if origin == self.replica_id or self._lease_alive(origin):
                    continue
            claimed = self._journal_root() / (
                f"{origin}{CLAIM_SEP}{self.replica_id}")
            try:
                os.rename(entry, claimed)
            except OSError:
                continue  # lost the claim race — exactly one renamer
                # wins, which is the no-double-ownership invariant
            LOG.warning("cluster: %s claimed journal of expired replica "
                        "%s (%s)", self.replica_id, origin, name)
            adopted += self._adopt(claimed, origin)
        self._reap_dead_leases()
        return adopted

    def _adopt(self, claimed: Path, origin: str) -> int:
        """Replay a claimed WAL through the existing journal machinery:
        clean finished verdicts are lifted into the shared store,
        unfinished entries re-enter this replica's admission (re-owned
        durably — see CheckingService.adopt_requests), then the claimed
        dir and the dead lease are removed so nothing is orphaned."""
        from .journal import AdmissionJournal
        from .request import DONE

        journal = AdmissionJournal(claimed)
        try:
            replayed = journal.replay()
        finally:
            journal.close()
        for sub, term in replayed["finished"]:
            if term.get("status") == DONE \
                    and isinstance(term.get("results"), list) \
                    and sub.get("fingerprint"):
                self.store.put(sub["fingerprint"], term["results"])
        taken = self.service.adopt_requests(replayed["unfinished"],
                                            origin=origin)
        # Stream sessions ride the same claim (ISSUE 12): re-journaled
        # under our lease, then restored as resumable stubs — the
        # producer's next append (404-failover finds us) resumes where
        # the dead replica's WAL left off.
        streams = replayed.get("streams") or {}
        streams_taken = self.service.streams.adopt(streams,
                                                   origin=origin)
        if taken < len(replayed["unfinished"]) \
                or streams_taken < len(streams):
            # our own shutdown interrupted the adoption: keep the
            # claimed dir (exclusively ours by the rename) so a peer —
            # or our restart — re-adopts once OUR lease expires; the
            # entries we did take are already re-journaled locally
            LOG.warning("cluster: adoption of %s interrupted after "
                        "%d/%d entries; claimed dir kept", origin,
                        taken, len(replayed["unfinished"]))
            return 0
        self.service._count("handoff_claims")
        shutil.rmtree(claimed, ignore_errors=True)
        try:
            self._lease_path(origin).unlink(missing_ok=True)
        except OSError:
            LOG.warning("cluster: dead lease removal failed for %s",
                        origin, exc_info=True)
        LOG.warning("cluster: %s adopted %d unfinished / %d finished "
                    "entries from %s (%d corrupt skipped)",
                    self.replica_id, len(replayed["unfinished"]),
                    len(replayed["finished"]), origin,
                    replayed["skipped"])
        return 1

    def _reap_dead_leases(self) -> None:
        """Remove expired leases with NO journal dir left behind (the
        journal-off ablation, or a handoff another replica completed):
        an expired lease must not advertise a ghost replica forever."""
        try:
            paths = sorted((self.root / "leases").glob("*.json"))
        except OSError:
            return
        for p in paths:
            lease = read_lease(p)
            if lease is None or not lease_expired(lease,
                                                  skew_s=self.skew_s):
                continue
            rid = str(lease.get("replica", ""))
            if rid == self.replica_id or not rid:
                continue
            if (self._journal_root() / rid).exists():
                continue  # handoff pending: the claim path owns cleanup
            try:
                p.unlink(missing_ok=True)
            except OSError:
                LOG.warning("cluster: stale lease reap of %s failed", p,
                            exc_info=True)

    # ----------------------------------------------------------- stats

    def stats(self) -> dict:
        live = live_replicas(self.root, skew_s=self.skew_s)
        return {
            "replica_id": self.replica_id,
            "live_replicas": len(live),
            "shed_depth": self.shed_depth,
            **self.store.stats(),
        }

"""graftd HTTP surface: stdlib http.server + JSON, no framework — the
same stance as `core/serve.py` (the results browser this daemon's trace
records feed).

Endpoints::

    POST /submit   {"workload": "register", "histories": [[op…]…],
                    "algorithm"?, "consistency"?, "deadline_ms"?,
                    "priority"?,
                    "run_dir"?}        → 200 {"id", "status", …}
                                       → 429 {"error", "retry_after_s"}
                                         (+ Retry-After header)
                                       → 400 {"error"} on malformed input
    GET  /result?id=ID[&wait_s=N]      → 200 request record (results
                                         included once terminal)
                                       → 404 unknown id
    POST /cancel   {"id": ID}          → 200 {"id", "status"}
    GET  /stats                        → 200 service counters
    GET  /healthz                      → 200 {"ok": true, "worker_alive"}

Streaming sessions (ISSUE 12)::

    POST /stream/open    {"workload"?, "units"?, "algorithm"?,
                          "consistency"?, "session"?, "resume"?}
                                       → 200 session state
                                       → 429 past the session cap
                                       → 409 id exists (without resume)
    POST /stream/append  {"session", "seq", "ops": [op…] | [[op…]…]}
                                       → 200 live state (violations
                                         surface HERE, mid-run)
                                       → 409 {"expected_seq"} on gaps /
                                         reused-seq payload mismatch
                                       → 429 {"retry_after_s"} over the
                                         session's segment/byte budget
    POST /stream/finish  {"session"}   → 200 final record (idempotent)
    GET  /stream/status?session=ID     → 200 session state

Binary ingest lane (ISSUE 18): ``POST /submit`` and ``POST
/stream/append`` additionally accept ``Content-Type:
application/x-jgraft-frame`` bodies — the length-delimited columnar
frames of `service/frame.py`, carrying CLIENT-encoded int32 tensors
that admission memoryview-slices zero-copy into the fingerprint path
(no JSON parse, no server-side encode). Malformed frames are 400s via
the same taxonomy as malformed JSON. The JSON surface is unchanged
byte for byte.

Same-host lane (ISSUE 18): `make_uds_server`/`serve_uds_in_thread`
bind the SAME handler over an AF_UNIX socket — no TCP stack, no
loopback port, one less copy per request.  ``JGRAFT_SERVICE_UDS=
/path.sock`` makes `serve_checker` listen on both.

Run it: ``python -m jepsen_jgroups_raft_tpu serve-checker`` (cli.py) or
embed via `make_server` (tests, the bench's --service mode).
"""

from __future__ import annotations

import hashlib
import json
import os
import socket
import socketserver
import stat
import threading
from functools import partial
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple

from ..platform import env_str
from .admission import QueueFull
from .daemon import CheckingService, ServiceStopped
from .stream import StreamBusy, StreamConflict

#: Submission body size cap (bytes): 64 MiB of JSON ops is far beyond
#: any legitimate history batch and bounds admission-side memory.
MAX_BODY_BYTES = 64 << 20

#: Content-Type of the binary columnar frames (service/frame.py).
FRAME_CONTENT_TYPE = "application/x-jgraft-frame"

#: Cap on blocking result waits (seconds) so a handler thread can never
#: be parked indefinitely by one client.
MAX_WAIT_S = 60.0

#: Retry-After hint on 503 ServiceStopped (ISSUE 8): a stopped daemon
#: is usually a restart in flight (supervisor, chaos harness, rolling
#: deploy), so the hint is restart-scale — clients with the idempotent
#: retry discipline come back after the journal replay instead of
#: erroring out of a survivable blip.
STOPPED_RETRY_AFTER_S = 2.0


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def __init__(self, *a, service: CheckingService, **kw):
        self.service = service
        super().__init__(*a, **kw)

    # ------------------------------------------------------- plumbing

    def _send(self, code: int, payload: dict,
              extra_headers: Optional[dict] = None) -> None:
        body = json.dumps(payload, default=str).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _raw_body(self) -> bytes:
        length = int(self.headers.get("Content-Length") or 0)
        if length > MAX_BODY_BYTES:
            raise ValueError(f"body too large ({length} bytes)")
        return self.rfile.read(length) if length else b""

    def _body(self) -> dict:
        raw = self._raw_body() or b"{}"
        payload = json.loads(raw)
        if not isinstance(payload, dict):
            raise ValueError("request body must be a JSON object")
        return payload

    def _query(self) -> Tuple[str, dict]:
        from urllib.parse import parse_qs, urlparse

        parsed = urlparse(self.path)
        return parsed.path, {k: v[-1]
                             for k, v in parse_qs(parsed.query).items()}

    def log_message(self, fmt, *args):
        pass  # quiet, like core/serve.py

    # ------------------------------------------------------- handlers

    def do_GET(self):
        path, q = self._query()
        if path == "/healthz":
            self._send(200, {"ok": True,
                             "worker_alive":
                             self.service.stats()["worker_alive"]})
            return
        if path == "/stats":
            self._send(200, self.service.stats())
            return
        if path == "/stream/status":
            try:
                self._send(200, self.service.streams.status(
                    q.get("session", "")))
            except KeyError:
                self._send(404, {"error": f"unknown stream session "
                                          f"{q.get('session', '')!r}"})
            return
        if path == "/result":
            req = self.service.get(q.get("id", ""))
            if req is None:
                self._send(404, {"error": f"unknown request id "
                                          f"{q.get('id', '')!r}"})
                return
            wait_s = q.get("wait_s")
            if wait_s is not None:
                try:
                    req.wait(min(float(wait_s), MAX_WAIT_S))
                except ValueError:
                    self._send(400, {"error": f"bad wait_s {wait_s!r}"})
                    return
            self._send(200, req.to_dict())
            return
        self._send(404, {"error": f"no such endpoint {path!r}"})

    def do_POST(self):
        path, _ = self._query()
        ctype = (self.headers.get("Content-Type")
                 or "").split(";")[0].strip().lower()
        if ctype == FRAME_CONTENT_TYPE:
            self._post_frame(path)
            return
        try:
            body = self._body()
        except (ValueError, json.JSONDecodeError) as e:
            self._send(400, {"error": f"bad request body: {e}"})
            return
        if path == "/submit":
            self._submit(body)
            return
        if path.startswith("/stream/"):
            self._stream(path, body)
            return
        if path == "/cancel":
            status = self.service.cancel(str(body.get("id", "")))
            if status is None:
                self._send(404, {"error": f"unknown request id "
                                          f"{body.get('id')!r}"})
            else:
                self._send(200, {"id": body.get("id"), "status": status})
            return
        self._send(404, {"error": f"no such endpoint {path!r}"})

    def _stream(self, path: str, body: dict) -> None:
        """Streaming-session endpoints (ISSUE 12). The error taxonomy
        mirrors /submit: flow control → 429 + Retry-After (the
        backoff-retrying client treats both surfaces uniformly),
        sequencing conflicts → 409 carrying `expected_seq`, malformed
        input → 400, unknown session → 404."""
        streams = self.service.streams
        handlers = {
            "/stream/open": lambda: streams.open(
                workload=str(body.get("workload", "register")),
                units=body.get("units", 1),
                algorithm=str(body.get("algorithm", "auto")),
                consistency=str(body.get("consistency",
                                         "linearizable")),
                session_id=body.get("session"),
                resume=bool(body.get("resume"))),
            "/stream/append": lambda: streams.append(
                str(body.get("session", "")), body.get("seq"),
                body.get("ops") or [],
                n_bytes=int(self.headers.get("Content-Length") or 0)),
            "/stream/finish": lambda: streams.finish(
                str(body.get("session", ""))),
        }
        handler = handlers.get(path)
        if handler is None:
            self._send(404, {"error": f"no such endpoint {path!r}"})
            return
        try:
            out = handler()
        except KeyError as e:
            self._send(404, {"error": f"unknown stream session "
                                      f"{e.args[0]!r}"})
            return
        except StreamBusy as e:
            self._send(429, {"error": str(e),
                             "retry_after_s": e.retry_after_s},
                       {"Retry-After": str(max(1, int(e.retry_after_s)))})
            return
        except StreamConflict as e:
            payload = {"error": str(e)}
            if e.expected_seq is not None:
                payload["expected_seq"] = e.expected_seq
            self._send(409, payload)
            return
        except (ValueError, TypeError) as e:
            self._send(400, {"error": f"{type(e).__name__}: {e}"})
            return
        self._send(200, out)

    def _submit(self, body: dict) -> None:
        try:
            # Inside the try: a non-numeric priority/deadline is a 400,
            # not an aborted connection.
            kwargs = {"algorithm": str(body.get("algorithm", "auto")),
                      "deadline_ms": body.get("deadline_ms"),
                      "priority": int(body.get("priority", 0)),
                      "consistency": str(body.get("consistency",
                                                  "linearizable"))}
            if body.get("run_dir"):
                req = self.service.submit_run_dir(
                    str(body["run_dir"]), workload=body.get("workload"),
                    **kwargs)
            else:
                req = self.service.submit(
                    body.get("histories") or [],
                    workload=str(body.get("workload", "register")),
                    **kwargs)
        except QueueFull as e:
            self._send(429, {"error": str(e),
                             "retry_after_s": e.retry_after_s},
                       {"Retry-After": str(max(1, int(e.retry_after_s)))})
            return
        except ServiceStopped as e:
            # retry_after_s surfaced exactly like the 429 path, so
            # ServiceClient's backoff treats both uniformly.
            self._send(503, {"error": str(e),
                             "retry_after_s": STOPPED_RETRY_AFTER_S},
                       {"Retry-After":
                        str(max(1, int(STOPPED_RETRY_AFTER_S)))})
            return
        except (ValueError, OSError, KeyError, TypeError) as e:
            # Malformed submissions (unknown workload, bad op rows,
            # unreadable run dir) are client errors, not daemon faults.
            self._send(400, {"error": f"{type(e).__name__}: {e}"})
            return
        self._send(200, req.to_dict(include_results=req.cached))

    def _post_frame(self, path: str) -> None:
        """Binary-frame POSTs (ISSUE 18). The error taxonomy MIRRORS
        the JSON handlers above per endpoint — `frame.FrameError` is a
        ValueError, so a torn/corrupt frame lands in the same 400 arm
        a malformed JSON body does; a client cannot tell the lanes
        apart by failure shape."""
        try:
            raw = self._raw_body()
        except ValueError as e:
            self._send(400, {"error": f"bad request body: {e}"})
            return
        if path == "/submit":
            try:
                req = self.service.submit_frame(raw)
            except QueueFull as e:
                self._send(429, {"error": str(e),
                                 "retry_after_s": e.retry_after_s},
                           {"Retry-After":
                            str(max(1, int(e.retry_after_s)))})
                return
            except ServiceStopped as e:
                self._send(503, {"error": str(e),
                                 "retry_after_s": STOPPED_RETRY_AFTER_S},
                           {"Retry-After":
                            str(max(1, int(STOPPED_RETRY_AFTER_S)))})
                return
            except (ValueError, OSError, KeyError, TypeError) as e:
                self._send(400, {"error": f"{type(e).__name__}: {e}"})
                return
            self._send(200, req.to_dict(include_results=req.cached))
            return
        if path == "/stream/append":
            from .frame import FrameError, SegmentFrame, decode_frame

            try:
                fr = decode_frame(raw)
                if not isinstance(fr, SegmentFrame):
                    raise FrameError("expected a stream-segment frame "
                                     "on /stream/append")
                # idempotency digest over the RAW frame bytes: a
                # retrying client re-sends the identical frame (the
                # encoder is deterministic), so a post-crash duplicate
                # compares equal — the binary twin of segment_digest.
                out = self.service.streams.append_binary(
                    fr.session, fr.seq, fr.units, n_bytes=len(raw),
                    digest=hashlib.sha256(raw).hexdigest())
            except KeyError as e:
                self._send(404, {"error": f"unknown stream session "
                                          f"{e.args[0]!r}"})
                return
            except StreamBusy as e:
                self._send(429, {"error": str(e),
                                 "retry_after_s": e.retry_after_s},
                           {"Retry-After":
                            str(max(1, int(e.retry_after_s)))})
                return
            except StreamConflict as e:
                payload = {"error": str(e)}
                if e.expected_seq is not None:
                    payload["expected_seq"] = e.expected_seq
                self._send(409, payload)
                return
            except (ValueError, TypeError) as e:
                self._send(400, {"error": f"{type(e).__name__}: {e}"})
                return
            self._send(200, out)
            return
        self._send(404, {"error": f"endpoint {path!r} does not accept "
                                  "binary frames"})


def make_server(service: CheckingService, host: str = "127.0.0.1",
                port: int = 0) -> Tuple[ThreadingHTTPServer, int]:
    """Bind the service's HTTP front (port 0 → ephemeral); the caller
    owns `serve_forever` (thread it for tests/bench)."""
    httpd = ThreadingHTTPServer((host, port),
                                partial(_Handler, service=service))
    return httpd, httpd.server_address[1]


class _UnixHTTPServer(ThreadingHTTPServer):
    """The same threading HTTP front over an AF_UNIX socket (ISSUE 18
    same-host lane): identical handlers and taxonomy, no TCP stack or
    loopback port between a co-located producer and the daemon.
    `server_bind` skips the TCP-specific getfqdn/port derivation (an
    AF_UNIX address is a filesystem path) and clears a STALE socket
    file first — the normal residue of a SIGKILL'd daemon; refusing to
    bind over it would turn every crash into a manual cleanup."""

    address_family = socket.AF_UNIX

    def server_bind(self):
        path = self.server_address
        try:
            if stat.S_ISSOCK(os.stat(path).st_mode):
                os.unlink(path)
        except FileNotFoundError:
            pass
        # NOT os.unlink unconditionally: a regular file at the path is
        # someone else's data — fail loudly instead of deleting it.
        socketserver.TCPServer.server_bind(self)
        self.server_name = "localhost"
        self.server_port = 0


def make_uds_server(service: CheckingService, path) -> _UnixHTTPServer:
    """Bind the service's unix-domain-socket front at `path`; the
    caller owns `serve_forever` and unlinking the socket after
    `server_close`."""
    return _UnixHTTPServer(str(path), partial(_Handler, service=service))


def serve_uds_in_thread(service: CheckingService, path):
    """Start the AF_UNIX front on a daemon thread; returns (httpd,
    thread). Shut down with `httpd.shutdown(); httpd.server_close()`
    (the socket file is unlinked by `server_close` callers — see
    `serve_checker` — or left for the next bind's stale-socket
    cleanup)."""
    httpd = make_uds_server(service, path)
    t = threading.Thread(target=httpd.serve_forever, daemon=True,
                         name="graftd-uds")
    t.start()
    return httpd, t


def serve_checker(store_root: str = "store", host: str = "0.0.0.0",
                  port: int = 8091,
                  queue_capacity: Optional[int] = None,
                  batch_wait: Optional[float] = None,
                  n_workers: Optional[int] = None,
                  cluster_dir: Optional[str] = None,
                  replica_id: Optional[str] = None) -> int:
    """CLI entry (`python -m jepsen_jgroups_raft_tpu serve-checker`):
    run graftd in the foreground until interrupted."""
    service = CheckingService(store_root=store_root,
                              queue_capacity=queue_capacity,
                              batch_wait=batch_wait,
                              n_workers=n_workers,
                              cluster_dir=cluster_dir,
                              replica_id=replica_id)
    httpd, bound = make_server(service, host, port)
    if service.cluster is not None and service.cluster.url is None:
        # Late-bind the advertised URL (the ephemeral port exists only
        # now) unless JGRAFT_SERVICE_ADVERTISE_URL pinned one; 0.0.0.0
        # is a bind address, not a reachable one — advertise loopback
        # for the single-host cluster recipes (docs/CI/chaos), real
        # fleets set the env to the host's routable address.
        reach = "127.0.0.1" if host in ("0.0.0.0", "::") else host
        service.cluster.set_url(f"http://{reach}:{bound}")
    uds_path = env_str("JGRAFT_SERVICE_UDS", "").strip()
    uds_httpd = None
    if uds_path:
        uds_httpd, _uds_thread = serve_uds_in_thread(service, uds_path)
    recovered = service.stats()["recovered_requests"]
    print(f"graftd: checking service on http://{host}:{bound}/ "
          f"(queue={service.queue.capacity}, "
          f"workers={service.n_workers}, store={store_root}, "
          f"journal={'on' if service._journal is not None else 'off'}"
          + (f", uds={uds_path}" if uds_path else "")
          + (f", cluster={service.cluster.replica_id}"
             if service.cluster is not None else "")
          + (f", recovered={recovered}" if recovered else "") + ")")
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.server_close()
        if uds_httpd is not None:
            uds_httpd.shutdown()
            uds_httpd.server_close()
            try:
                os.unlink(uds_path)
            except OSError:
                pass  # already gone / replaced by a newer bind
        service.shutdown(wait=True)
    return 0


def serve_in_thread(service: CheckingService, host: str = "127.0.0.1",
                    port: int = 0):
    """Start the HTTP front on a daemon thread; returns (httpd, port,
    thread). Tests and the bench use this; shut down with
    `httpd.shutdown(); httpd.server_close()`."""
    httpd, bound = make_server(service, host, port)
    t = threading.Thread(target=httpd.serve_forever, daemon=True,
                         name="graftd-http")
    t.start()
    return httpd, bound, t

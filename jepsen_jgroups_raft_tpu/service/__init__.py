"""graftd: the multi-tenant checking service (ISSUE-5 tentpole).

The paper's harness checks one recorded history per run; this package
turns the checker into an always-on daemon that amortizes the chunked
TPU scan (PR 3/4's ChunkLaunch dispatch, pow2+midpoint shape buckets,
macro compaction) across many independent submissions:

* request.py   — admission-time normalization: encode once, fingerprint
                 the packed tensors, per-key split for independent
                 workloads.
* admission.py — bounded queue with reject-with-retry-after
                 backpressure + the LRU result cache.
* scheduler.py — cross-request shape-bucket batching over
                 `checker.linearizable.check_encoded`, deadline/aging
                 ordering, per-request cancellation, degrade-to-CPU.
* journal.py   — write-ahead admission journal (ISSUE 8): fsync'd
                 submit records before the 202, terminal markers,
                 bounded compaction, loud torn-tail replay.
* daemon.py    — CheckingService: supervised worker, crash recovery,
                 poison-batch quarantine + hung-batch watchdog, stats,
                 store/ trace records.
* http.py      — stdlib HTTP+JSON surface (`serve-checker` CLI).
* client.py    — tenant-side client with idempotent retry/backoff and
                 cluster routing (affinity-first, least-loaded
                 fallback, cluster-global attempt cap).
* store.py     — shared content-addressed result store (ISSUE 11):
                 fingerprint → verdict entries any replica reads and
                 writes atomically; per-row detail records for the
                 distributed wavefront's witness exchange.
* cluster.py   — replica membership leases, load shedding with the
                 cluster's best retry-after, and cross-replica journal
                 handoff (claim-by-rename, replay, re-own).
* stream.py    — streaming verdict sessions (ISSUE 12): session-keyed
                 segment ingest, per-segment greedy fast path, carried
                 chunk-scan re-entry, live mid-run violation surfacing,
                 WAL-backed crash resume and idle-park, per-session
                 flow-control budgets.
* frame.py     — wire-speed ingest (ISSUE 18): length-prefixed binary
                 columnar frames (client-side encode, zero-copy server
                 decode, CRC-guarded); the server always re-derives the
                 fingerprint, so a lying client corrupts only its own
                 verdict.
"""

from .admission import QueueFull, ServiceStopped  # noqa: F401
from .client import ServiceClient, ServiceError  # noqa: F401
from .client import StreamSession as ClientStreamSession  # noqa: F401
from .cluster import ClusterManager, discover_replica_urls  # noqa: F401
from .daemon import CheckingService  # noqa: F401
from .http import make_server, serve_checker, serve_in_thread  # noqa: F401
from .journal import AdmissionJournal, journal_enabled  # noqa: F401
from .request import CheckRequest  # noqa: F401
from .store import ResultStore  # noqa: F401
from .stream import StreamBusy, StreamConflict, StreamManager  # noqa: F401

"""Shared content-addressed result store: the cluster tier's cache
substrate (ISSUE 11 tentpole (a)).

PR 8's WAL terminal records are already persisted cache entries — a
clean DONE marker carries the verdict list keyed by the submission's
sha256 fingerprint. This module lifts that idea out of the per-daemon
journal into a filesystem store ANY replica can read and write, so a
verdict computed once is a cache hit fleet-wide and a cold-started
replica warms from the store instead of re-checking from the wire.

Layout (under the cluster dir every replica shares)::

    <root>/results/<fp[:2]>/<fp>.json    # request-level verdict lists
    <root>/detail/<fp[:2]>/<fp>.json     # per-ROW result details
                                         # (the multi-host wavefront's
                                         # witness/counterexample
                                         # exchange — tentpole (d))

Design points, each load-bearing:

* **Writes are atomic and first-wins.** An entry is written to a
  uniquely-named temp file in the same directory and published with
  ``os.replace`` — a reader never observes a half-written entry, and a
  crash mid-put leaves either no entry or a whole one. Two replicas
  racing the same fingerprint is the NORMAL case (idempotent
  resubmission fanned across the fleet): the writer that finds a valid
  entry already published discards its own copy (the verdicts are
  deterministic over the fingerprinted bytes, so either copy is
  correct — first-wins just avoids the pointless churn).
* **Entries carry a CRC and corrupt entries are never file-fatal.** A
  torn tail or bit-rotted entry costs exactly that entry: reads skip
  it LOUDLY (logged + counted) and report a miss; a later put heals it
  via the same atomic replace. One bad entry must never take down a
  replica or the store.
* **Degraded verdicts are never stored.** The same rule the LRU cache
  and the WAL terminal records apply: a ``platform-degraded`` stamp
  describes the run that produced it, not a future replay on a healthy
  replica — a degraded verdict served fleet-wide would poison every
  replica's answers for that fingerprint.

The store is INERT unless a cluster dir is configured
(``JGRAFT_SERVICE_CLUSTER_DIR``, or the daemon's ``cluster_dir``
argument); single-replica graftd never touches this module.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from pathlib import Path
from typing import List, Optional, Sequence

LOG = logging.getLogger("jgraft.service")

#: Store schema version; reads refuse entries from a NEWER version
#: loudly (miss + count) instead of misparsing them.
STORE_VERSION = 1


def cluster_dir() -> Optional[str]:
    """The configured shared cluster directory, or None (the inert
    default — single-replica graftd)."""
    from ..platform import env_str

    return env_str("JGRAFT_SERVICE_CLUSTER_DIR") or None


def _crc_entry(rec: dict) -> str:
    """Canonical CRC32 over the entry minus its own crc field (the same
    rule as the WAL's record CRC — service/journal.py)."""
    from .journal import _crc_line

    return _crc_line(rec)


def is_degraded(results: Sequence[dict]) -> bool:
    """The never-persist rule's predicate (module docstring)."""
    return any("platform-degraded" in r for r in results)


def detail_fingerprint(model, algorithm: str, enc) -> str:
    """Row-level content key for the detail exchange: one encoded unit
    hashed exactly like a single-unit submission (service/request.py),
    so the key is derivable by every process holding the same batch —
    the SPMD contract of `run_sharded` guarantees they all do."""
    from .request import fingerprint_encodings

    return fingerprint_encodings(model, algorithm, [enc])


class ResultStore:
    """Filesystem-backed fingerprint → verdict store (module
    docstring). Thread-safe; every method is best-effort against IO
    failures — a store that raises would convert a disk hiccup into a
    checking outage, the exact conversion the journal refuses too."""

    def __init__(self, root):
        self.root = Path(root)
        self._lock = threading.Lock()
        self._counters = {"store_get_hits": 0,  # guarded_by(_lock)
                          "store_get_misses": 0,
                          "store_put_writes": 0, "store_put_discards": 0,
                          "store_corrupt_skipped": 0, "store_io_errors": 0}
        try:
            (self.root / "results").mkdir(parents=True, exist_ok=True)
            (self.root / "detail").mkdir(parents=True, exist_ok=True)
        except OSError:
            self._count("store_io_errors")
            LOG.warning("result store %s: layout mkdir failed",
                        self.root, exc_info=True)

    # ----------------------------------------------------------- paths

    def _entry_path(self, kind: str, fingerprint: str) -> Path:
        # two-level fan-out: one flat dir of millions of fingerprints
        # makes every listdir/rewrite O(fleet); 256 shards keep each
        # directory north-star-scale friendly
        return self.root / kind / fingerprint[:2] / f"{fingerprint}.json"

    # ----------------------------------------------------------- read

    def _read(self, kind: str, fingerprint: str) -> Optional[dict]:
        """Parsed valid entry or None. Corrupt/torn entries are skipped
        LOUDLY and never fatal; a racing writer's `os.replace` means a
        missing file between exists() and read is an ordinary miss."""
        path = self._entry_path(kind, fingerprint)
        try:
            raw = path.read_bytes()
        except FileNotFoundError:
            return None
        except OSError:
            self._count("store_io_errors")
            LOG.warning("result store: read of %s failed", path,
                        exc_info=True)
            return None
        try:
            rec = json.loads(raw)
            if not isinstance(rec, dict):
                raise ValueError("store entry is not an object")
            if int(rec.get("v", -1)) > STORE_VERSION:
                raise ValueError(
                    f"entry version {rec.get('v')} is newer than this "
                    f"replica ({STORE_VERSION})")
            if rec.get("crc") != _crc_entry(rec):
                raise ValueError("crc mismatch (torn or rotted entry)")
            if rec.get("fingerprint") != fingerprint:
                raise ValueError("entry fingerprint does not match its "
                                 "path (misfiled entry)")
        except (ValueError, json.JSONDecodeError) as e:
            self._count("store_corrupt_skipped")
            LOG.warning("result store: corrupt entry %s skipped: %s",
                        path, e)
            return None
        return rec

    def get(self, fingerprint: str) -> Optional[List[dict]]:
        """Verdict list for a fingerprint, or None (miss / corrupt)."""
        rec = self._read("results", fingerprint)
        if rec is None or not isinstance(rec.get("results"), list):
            self._count("store_get_misses")
            return None
        self._count("store_get_hits")
        return [dict(r) for r in rec["results"]]

    def get_detail(self, fingerprint: str) -> Optional[dict]:
        """Per-row result detail (witness, counterexample, kernel tag)
        for the distributed wavefront's remote rows, or None."""
        rec = self._read("detail", fingerprint)
        if rec is None or not isinstance(rec.get("result"), dict):
            return None
        return dict(rec["result"])

    # ----------------------------------------------------------- write

    def _publish(self, kind: str, fingerprint: str, body: dict) -> bool:
        """First-wins atomic publish (module docstring): discard when a
        VALID entry already exists; write temp + `os.replace` otherwise
        (healing a corrupt entry in place — replace is atomic, so a
        concurrent healthy writer cannot be half-overwritten)."""
        if self._read(kind, fingerprint) is not None:
            self._count("store_put_discards")
            return False
        rec = dict(body, v=STORE_VERSION, fingerprint=fingerprint)
        rec["crc"] = _crc_entry(rec)
        path = self._entry_path(kind, fingerprint)
        tmp = path.with_name(
            f".{fingerprint}.{os.getpid()}.{threading.get_ident()}.tmp")
        try:
            path.parent.mkdir(parents=True, exist_ok=True)
            with open(tmp, "w") as fh:
                json.dump(rec, fh, sort_keys=True,
                          separators=(",", ":"))
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, path)
        except OSError:
            self._count("store_io_errors")
            LOG.warning("result store: publish of %s failed", path,
                        exc_info=True)
            try:
                os.unlink(tmp)
            except OSError:
                pass  # temp already gone (replace landed) or unwritable
            return False
        self._count("store_put_writes")
        return True

    def put(self, fingerprint: str, results: Sequence[dict]) -> bool:
        """Store a clean verdict list; degraded verdicts are refused
        (never-persist rule). True when this call published the entry."""
        if is_degraded(results):
            return False
        from ..core.store import _jsonable

        return self._publish("results", fingerprint,
                             {"results": _jsonable(list(results))})

    def put_detail(self, fingerprint: str, result: dict) -> bool:
        """Store one row's full result detail (tentpole (d)); same
        degraded gate as `put`."""
        if is_degraded([result]):
            return False
        from ..core.store import _jsonable

        return self._publish("detail", fingerprint,
                             {"result": _jsonable(dict(result))})

    # ----------------------------------------------------------- stats

    def _count(self, key: str) -> None:
        with self._lock:
            self._counters[key] += 1

    def stats(self) -> dict:
        with self._lock:
            return dict(self._counters)


_DETAIL_STORE_CACHE: dict = {}
_DETAIL_STORE_LOCK = threading.Lock()


def detail_store() -> Optional[ResultStore]:
    """Process-cached store for the distributed wavefront's detail
    exchange (parallel/distributed.run_sharded). Configured by
    ``JGRAFT_RESULT_STORE`` (a store dir shared across the pod's hosts)
    falling back to the cluster dir; None — the inert default — keeps
    remote rows as the PR 7 verdict-code stubs."""
    from ..platform import env_str

    raw = env_str("JGRAFT_RESULT_STORE") or cluster_dir()
    if not raw:
        return None
    with _DETAIL_STORE_LOCK:
        store = _DETAIL_STORE_CACHE.get(raw)
        if store is None:
            store = ResultStore(raw)
            _DETAIL_STORE_CACHE[raw] = store
        return store

"""Batching scheduler: cross-request coalescing over the chunked scan.

The substrate PR 3/4 built — chunked `ChunkLaunch` dispatch with
decided-row eviction, pow2+midpoint shape buckets, macro-event
compaction — amortizes kernel work across the ROWS of one caller's
batch. This module extends the amortization across CALLERS: pending
requests whose encodings pack into the same shape bucket are coalesced
into one `check_encoded` batch, so many small tenant histories ride a
single dense/sort/Pallas launch; per-request verdicts are demuxed back
by row count after the wavefront evicts them.

Soundness of the coalescing (doc/checker-design.md §8): every kernel
family treats the batch axis as fully independent — rows never exchange
state (frontier carries are per-row, eviction/recompaction is a gather
over rows, window grouping only re-orders rows between launches) — so
the verdict of a row is a function of that row's event stream alone,
and a demuxed verdict is bitwise-identical to the verdict of the same
history checked in isolation (pinned by tests/test_service.py
differentials).

Ordering: requests are served by EFFECTIVE deadline
``min(deadline, submitted + aging_cap) - priority_credit·priority`` —
the deadline drives urgency, the aging cap bounds how long a
far-deadline request can be overtaken (starvation-free: after
`AGING_CAP_S` of waiting, a request's key stops growing and arrival
time breaks ties), and priority buys a fixed head start rather than a
strict class (a priority flood cannot starve the plain tier forever).
A batch is formed from the head request's shape bucket; when it is
small and the head deadline is not imminent, the scheduler lingers
``JGRAFT_SERVICE_BATCH_WAIT_MS`` for more same-bucket arrivals — the
classic batching-window trade (latency of the head vs occupancy of the
launch).

Resilience: the device path failing MID-CHECK (tunnel drop, injected
fault) degrades the batch to the host-only ladder
(`checker.linearizable.check_encoded_host` — CPU frontier, budgeted
DFS), stamping ``platform-degraded`` into every affected result and
recording the root cause via `platform.note_degraded`; the request
completes with a sound verdict instead of erroring.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, List, Optional

from ..checker import autotune
from ..checker.schedule import stats_scope
from ..history.packing import bucket_rows
from ..platform import env_int, is_backend_init_failure, note_degraded
from .admission import AdmissionQueue
from .request import CANCELLED, DONE, FAILED, RUNNING, CheckRequest

LOG = logging.getLogger("jgraft.service")

#: Default linger for batch formation (ms). Small against check time,
#: large against localhost submit bursts: concurrent tenants submitting
#: within one RPC round trip coalesce, a lone request pays ≤ this.
DEFAULT_BATCH_WAIT_MS = 50

#: A request waiting this long is as urgent as scheduling ever treats
#: it (its effective deadline stops receding) — the starvation bound.
AGING_CAP_S = 30.0

#: Seconds of deadline credit per priority unit.
PRIORITY_CREDIT_S = 1.0

#: Cap on rows (check units) per coalesced launch batch.
DEFAULT_MAX_BATCH_ROWS = 256


class WatchdogDegrade(Exception):
    """Internal signal: the hung-batch watchdog marked this retry
    ``force_host`` — route it through the same degrade arm a dying
    device path takes (host ladder + ``platform-degraded`` stamp, never
    cached). Not a platform failure, so the process-wide degrade
    registry is never written for it."""


class ShardLoads:
    """Load accounting for graftd's worker shards (ISSUE 7 tentpole
    (c)). A shard is one execution lane — one worker thread per
    host/device group — and its load is the rows dispatched to it and
    not yet finished. `least_loaded` is the routing rule the daemon's
    dispatcher applies to every formed batch: independent shape-bucket
    batches land on different shards and check CONCURRENTLY instead of
    serializing through one worker. Deterministic (ties break to the
    lowest shard id) so placement is testable; thread-safe (the
    executors release from their own threads)."""

    def __init__(self, n_shards: int):
        self.n_shards = max(1, int(n_shards))
        self._loads = [0] * self.n_shards  # guarded_by(_lock)
        self._lock = threading.Lock()

    def least_loaded(self) -> int:
        with self._lock:
            return min(range(self.n_shards), key=lambda k: self._loads[k])

    def add(self, shard: int, rows: int) -> None:
        with self._lock:
            self._loads[shard] += rows

    def done(self, shard: int, rows: int) -> None:
        with self._lock:
            self._loads[shard] = max(0, self._loads[shard] - rows)

    def snapshot(self) -> List[int]:
        with self._lock:
            return list(self._loads)


def batch_wait_s() -> float:
    """Resolved linger window (JGRAFT_SERVICE_BATCH_WAIT_MS; defensive
    parse — garbage warns and keeps the default)."""
    return env_int("JGRAFT_SERVICE_BATCH_WAIT_MS", DEFAULT_BATCH_WAIT_MS,
                   minimum=0) / 1000.0


def effective_deadline(req: CheckRequest,
                       aging_cap_s: float = AGING_CAP_S) -> float:
    """Scheduling key (smaller = sooner). See module docstring."""
    return (min(req.deadline, req.submitted + aging_cap_s)
            - PRIORITY_CREDIT_S * req.priority)


def bucket_signature(req: CheckRequest) -> tuple:
    """Shape bucket a request's rows pack into — the coalescing key.

    Two requests with the same signature ride one `check_encoded` batch
    whose group packing pads them into shared jit-cache shapes: same
    model family (one kernel family), same algorithm, the same
    consistency rung (a weaker rung relaxes the WHOLE batch's streams
    before the kernels see them — checker/consistency.py — so mixed
    rungs cannot share a launch), and the same pow2+midpoint EVENT
    bucket (`bucket_rows(E, 32)` — the floor_e=32 series
    `pad_batch_bucketed` pads short groups to). Window grouping inside
    the checker re-buckets rows further by concurrency window; that is
    invisible here because it happens after concatenation. Mixed-MODEL
    submissions need no scheduler changes: different models simply form
    different buckets, each riding the same formation/linger/execute
    machinery (the ISSUE-10 acceptance row pins this)."""
    e_max = max((e.n_events for e in req.encs), default=0)
    return (type(req.model).__name__, req.algorithm, req.consistency,
            bucket_rows(max(e_max, 1), 32))


class BatchScheduler:
    """Forms and executes coalesced batches from an AdmissionQueue."""

    def __init__(self, queue: AdmissionQueue,
                 check_fn: Optional[Callable] = None,
                 host_fallback: Optional[Callable] = None,
                 max_batch_rows: Optional[int] = None,
                 batch_wait: Optional[float] = None,
                 aging_cap_s: float = AGING_CAP_S):
        from ..checker.linearizable import check_encoded, check_encoded_host

        def _check_local(encs, model, algorithm="auto",
                         consistency="linearizable", lin_fastpath=None):
            # distribute=False: graftd's admission queue is HOST-local
            # — different daemon processes hold different batches, so
            # the cross-host SPMD seam (which barriers on every process
            # checking the SAME batch) would deadlock a clustered
            # daemon. Multi-host graftd is shard-routed per host
            # instead: one daemon per host, each with its own workers
            # (doc/checker-design.md §10).
            return check_encoded(encs, model, algorithm=algorithm,
                                 distribute=False,
                                 consistency=consistency,
                                 lin_fastpath=lin_fastpath)

        #: device-path seam (tests inject failures / gates here).
        self.check_fn = check_fn or _check_local
        self.host_fallback = host_fallback or check_encoded_host
        #: graftd fast lane (ISSUE 14): enabled only on the DEFAULT
        #: check path — an injected check_fn is a test/ops seam that
        #: must observe every batch, so the lane never short-circuits
        #: it. `_default_host_fallback` gates whether the degrade arm
        #: may receive the lin_fastpath kwarg (an injected fallback
        #: predates it).
        self.fastlane_enabled = check_fn is None
        self._default_host_fallback = host_fallback is None
        self.max_batch_rows = (max_batch_rows if max_batch_rows is not None
                               else env_int("JGRAFT_SERVICE_MAX_BATCH_ROWS",
                                            DEFAULT_MAX_BATCH_ROWS,
                                            minimum=1))
        self.batch_wait = (batch_wait if batch_wait is not None
                           else batch_wait_s())
        self.aging_cap_s = aging_cap_s
        self.queue = queue
        self._seq = 0  # guarded_by(_seq_lock)
        self._seq_lock = threading.Lock()

    # ------------------------------------------------------ formation

    def _choose(self, pending: List[CheckRequest]) -> List[CheckRequest]:
        """Head request by effective deadline, plus every same-bucket
        request that fits the row cap, in deadline order. A ``solo``
        request (poison-batch quarantine split, watchdog force-host
        retry — ISSUE 8) never coalesces: it forms a singleton batch so
        a deterministically-crashing rider cannot take innocent
        neighbors down with it again."""
        ordered = sorted(pending, key=lambda r: (
            effective_deadline(r, self.aging_cap_s), r.submitted))
        head = ordered[0]
        if head.solo:
            return [head]
        sig = bucket_signature(head)
        batch, rows = [], 0
        for r in ordered:
            if r.solo or bucket_signature(r) != sig:
                continue
            if batch and rows + r.n_rows > self.max_batch_rows:
                break
            batch.append(r)
            rows += r.n_rows
        return batch

    def fastlane(self, batch: List[CheckRequest]
                 ) -> tuple[List[CheckRequest], List[CheckRequest]]:
        """graftd fast lane (ISSUE 14): certify each popped request's
        rows on the host BEFORE the batch lingers, occupies a shard
        queue, or launches a kernel. Returns ``(decided, live)`` —
        decided requests are already finished DONE (sub-batch-latency
        verdicts; the daemon accounts/traces them), live ones proceed
        to the ordinary coalesced launch with the redundant in-checker
        fast path suppressed (execute passes ``lin_fastpath=False``).

        All-or-nothing per request: a partially-certifiable request
        stays live whole — its rows ride one launch and demux by row
        count, so evicting a subset would tear the fingerprint/trace
        contract. The abort budget + per-bucket gating inside
        `lin_fastpath_pass` bound what a hopeless request costs here.
        Tier attribution is noted HERE, only for delivered requests
        (``note=False`` in the pass): a discarded partial result's
        rows are decided — and attributed — by the kernel launch they
        proceed to, never double-counted."""
        from ..checker.linearizable import (LIN_FASTPATH_ALGOS,
                                            lin_fastpath_on,
                                            lin_fastpath_pass)
        from ..checker.schedule import note_tier

        if not batch or not self.fastlane_enabled \
                or not lin_fastpath_on():
            return [], batch
        from ..checker.base import VALID

        decided, live = [], []
        for r in batch:
            if (r.terminal or r.cancelled.is_set()
                    or r.consistency != "linearizable"
                    or r.algorithm not in LIN_FASTPATH_ALGOS
                    or r.force_host or not r.encs):
                live.append(r)
                continue
            t0 = time.monotonic()
            rs = lin_fastpath_pass(r.encs, r.model, note=False)
            # the pass deliberately leaves 0-event rows undecided (the
            # kernel path stamps them "trivial"); here they are
            # host-decidable for free and must not force an otherwise
            # fully-certified request onto the batch path
            for j, enc in enumerate(r.encs):
                if rs[j] is None and enc.n_events <= 0:
                    rs[j] = {"valid?": VALID, "algorithm": "trivial",
                             "op-count": 0, "decided-tier": "trivial"}
            # the lane SCANNED this request: execute() may suppress the
            # redundant in-checker re-scan for it (and only for it)
            r._fp_tried = True
            if not all(res is not None for res in rs):
                live.append(r)
                continue
            wall = time.monotonic() - t0
            # honor a cancel that landed DURING the scan — the batch
            # path's demux re-checks at the same point (first-wins
            # finish keeps the race harmless either way)
            if r.cancelled.is_set():
                r.finish(CANCELLED)
                decided.append(r)
                continue
            tiers: dict = {}
            for res in rs:
                t = res["decided-tier"]
                tiers[t] = tiers.get(t, 0) + 1
                note_tier(t, wall_s=wall / max(len(rs), 1))
            r.stats = {
                "fastlane": True,
                "batched_requests": 0,
                "batch_rows": r.n_rows,
                "batch_wall_s": round(wall, 4),
                "decided_tier": tiers,
                "placement": {"shard": None, "n_shards": 0},
                "degraded": False,
            }
            r.finish(DONE, results=rs)
            decided.append(r)
        return decided, live

    def next_batch(self, timeout: float,
                   on_decided=None) -> List[CheckRequest]:
        """Block up to `timeout` for a batch. After the first pick, if
        the launch is far from full and the head's deadline allows,
        linger one batch-wait window and sweep in same-bucket arrivals
        (deadline order is preserved: the linger only ever ADDS rows to
        the head's launch, it never reorders across buckets).

        ``on_decided`` (ISSUE 14): when given, the fast lane certifies
        the popped requests FIRST — before the linger, so a decided
        request's latency is the host scan, not the batching window —
        and decided requests are delivered to the callback instead of
        the returned batch (linger top-ups ride the lane too)."""
        batch = self.queue.take(self._choose, timeout)
        if not batch:
            return batch
        if on_decided is not None:
            done, batch = self.fastlane(batch)
            if done:
                on_decided(done)
            if not batch:
                return []
        head = batch[0]
        rows = sum(r.n_rows for r in batch)
        slack = head.deadline - time.monotonic()
        if (self.batch_wait > 0 and rows < self.max_batch_rows
                and not head.solo and slack > self.batch_wait):
            time.sleep(self.batch_wait)
            sig = bucket_signature(head)

            def topup(pending: List[CheckRequest]) -> List[CheckRequest]:
                extra, extra_rows = [], rows
                for r in sorted(pending, key=lambda r: (
                        effective_deadline(r, self.aging_cap_s),
                        r.submitted)):
                    if r.solo or bucket_signature(r) != sig:
                        continue
                    if extra_rows + r.n_rows > self.max_batch_rows:
                        break
                    extra.append(r)
                    extra_rows += r.n_rows
                return extra

            extra = self.queue.take(topup, timeout=0.0)
            if on_decided is not None and extra:
                done, extra = self.fastlane(extra)
                if done:
                    on_decided(done)
            batch.extend(extra)
        # Requests cancelled between pop and here stay in the batch:
        # execute() finalizes them as CANCELLED (dropping them silently
        # would leave their waiters blocked forever).
        return batch

    # ------------------------------------------------------ execution

    def execute(self, batch: List[CheckRequest],
                placement: Optional[dict] = None) -> dict:
        """Run one coalesced batch and demux; returns batch-level stats
        for the daemon's counters. Cancelled requests are finalized
        without results (a cancel landing mid-chunk is honored at
        demux: the row work is already spent, the verdict is simply
        not delivered). `placement` (the daemon's shard-routing record:
        shard id, shard count, loads at dispatch) is stamped into every
        request's stats so a tenant's trace shows WHERE its launch ran."""
        live = []
        for r in batch:
            if r.terminal:
                # stale watchdog-requeue twin: the other copy already
                # delivered the client-visible result (finish is
                # first-wins) — nothing to execute or finalize.
                continue
            if r.cancelled.is_set():
                r.finish(CANCELLED)
            else:
                r.status = RUNNING
                r.run_started = time.monotonic()
                live.append(r)
        if not live:
            return {"requests": 0, "rows": 0, "degraded": False,
                    "wall_s": 0.0, "tiers": {}}
        with self._seq_lock:
            self._seq += 1
            seq = self._seq
        encs = [e for r in live for e in r.encs]
        model = live[0].model
        algorithm = live[0].algorithm
        consistency = live[0].consistency
        # Weaker-rung batches pass the knob through; the default rung
        # keeps the historical check_fn arity (injected seams predate
        # the consistency parameter).
        check_kw = ({"consistency": consistency}
                    if consistency != "linearizable" else {})
        if consistency == "linearizable" and self.fastlane_enabled \
                and live and all(getattr(r, "_fp_tried", False)
                                 for r in live):
            # ISSUE 14: the dispatch fast lane actually SCANNED every
            # request in this batch — the in-checker fast path
            # re-scanning them inside check_encoded would be the
            # double-scan the rung-skip satellite closes. Requests the
            # lane skipped WITHOUT scanning (force_host retries,
            # cancelled-at-pop, non-kernel algorithms) keep the
            # checker/host-ladder fast path: for them nothing was
            # tried yet. Only on the default check path (injected
            # seams keep their arity).
            check_kw["lin_fastpath"] = False
        host_kw = dict(check_kw)
        if not self._default_host_fallback:
            host_kw.pop("lin_fastpath", None)
        label = "graftd:" + ",".join(r.id for r in live)
        degraded_note_local = None
        # Autotune consult marker (PR 6): the checker applies per-bucket
        # plans inside check_encoded; snapshot the applied-plan SEQUENCE
        # (not the bounded log's length — that pins at the bound once
        # trimming starts). Entries are additionally filtered to THIS
        # thread (ISSUE 7): with multiple shard executors running
        # concurrently, "everything after the mark" would include
        # neighbor shards' plans — the thread filter keeps each batch's
        # stamp to exactly the plans its own launch consulted.
        autotune_mark = autotune.applied_seq()
        t0 = time.monotonic()
        with stats_scope(label=label) as scan:
            try:
                if any(r.force_host for r in live):
                    # Hung-batch watchdog second strike (ISSUE 8): the
                    # first requeue re-ran the device path and it hung
                    # again, so this retry goes STRAIGHT to the bounded
                    # host ladder — a slower sound verdict instead of a
                    # third chance to wedge a shard. Raising
                    # WatchdogDegrade reuses the degrade arm below
                    # verbatim (stamped degraded, therefore never
                    # cached).
                    raise WatchdogDegrade(
                        "hung batch exceeded its deadline twice; "
                        "watchdog forced the host ladder")
                results = self.check_fn(encs, model, algorithm=algorithm,
                                        **check_kw)
            except Exception as e:
                # Device path died mid-check (tunnel drop, backend
                # teardown, injected fault): degrade THIS batch to the
                # host-only ladder — a slower sound verdict beats a
                # failed request. The stamp is LOCAL to this batch's
                # results; the process-wide first-note-wins registry is
                # only written for platform-level failures (backend
                # init / tunnel-drop flavors), where "this process is
                # degraded" is genuinely true of later batches too — a
                # one-off non-platform error must not poison every
                # healthy verdict a long-lived daemon produces after it
                # (check_encoded stamps all results whenever the
                # registry holds a note).
                LOG.warning("graftd batch seq=%d device path failed; "
                            "degrading %d rows to host CPU",
                            seq, len(encs), exc_info=True)
                degraded_note_local = (
                    f"graftd degraded to host CPU mid-check: "
                    f"{type(e).__name__}: {e}"[:300])
                if is_backend_init_failure(e):
                    note_degraded(degraded_note_local)
                results = [self.host_fallback(enc, model, **host_kw)
                           for enc in encs]
                for res in results:
                    res["platform-degraded"] = degraded_note_local
        wall = time.monotonic() - t0
        scan_counters = {k: v for k, v in scan.items()
                         if k not in ("label", "tiers")}
        autotune_plans = autotune.applied_since(
            autotune_mark, thread_id=threading.get_ident())
        batch_tiers: dict = {}
        cursor = 0
        for r in live:
            mine = results[cursor:cursor + r.n_rows]
            cursor += r.n_rows
            # Tier attribution (ISSUE 13): which decision-ladder tier
            # decided each of this request's rows — the per-request
            # trace record's capacity-model evidence, aggregated
            # daemon-wide into /stats decided_tier.
            tiers: dict = {}
            for res in mine:
                t = res.get("decided-tier") if res else None
                if t is not None:
                    tiers[t] = tiers.get(t, 0) + 1
                    batch_tiers[t] = batch_tiers.get(t, 0) + 1
            r.stats = {
                "batched_requests": len(live),
                "batch_rows": len(encs),
                "batch_seq": seq,
                "batch_wall_s": round(wall, 4),
                "scan": dict(scan_counters, label=label),
                "autotune_plans": autotune_plans,
                "decided_tier": tiers,
                "placement": dict(placement) if placement else
                {"shard": 0, "n_shards": 1},
                "degraded": degraded_note_local is not None,
            }
            if r.cancelled.is_set():
                r.finish(CANCELLED)
            elif any(res is None for res in mine):
                r.finish(FAILED, error="checker returned no verdict")
            else:
                self._attach_counterexamples(r, mine)
                r.finish(DONE, results=mine)
        return {"requests": len(live), "rows": len(encs),
                "degraded": degraded_note_local is not None,
                "wall_s": wall, "seq": seq, "tiers": batch_tiers}

    #: Skip counterexample minimization for units beyond this many ops:
    #: the greedy pair-drop is bounded anyway (counterexample.py caps),
    #: but even the suffix-truncation re-search costs a CPU frontier
    #: pass — a tenant submitting huge invalid histories should not
    #: stall the shard's demux.
    MAX_COUNTEREXAMPLE_OPS = 2048

    def _attach_counterexamples(self, r: CheckRequest, mine: list) -> None:
        """ISSUE-10 satellite: a `fail` verdict leaving graftd (result
        record AND trace record — the daemon writes traces from the
        same result lists) carries the minimized witness
        (checker/counterexample.py), not a raw op dump. Best-effort:
        explanation failures must never take down a sound verdict."""
        from ..checker.base import INVALID
        from ..checker.counterexample import attach_counterexample
        from ..checker.linearizable import DEFAULT_MAX_CPU_CONFIGS

        for (label, hist), res in zip(r.units, mine):
            if res.get("valid?") is not INVALID:
                continue
            if res.get("op-count", 0) > self.MAX_COUNTEREXAMPLE_OPS:
                continue
            try:
                attach_counterexample(res, hist, r.model,
                                      max_cpu_configs=
                                      DEFAULT_MAX_CPU_CONFIGS,
                                      consistency=r.consistency)
            except Exception:
                LOG.warning("counterexample attach failed for %s/%s",
                            r.id, label, exc_info=True)

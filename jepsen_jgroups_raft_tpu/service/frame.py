"""Binary columnar submission frames: graftd's wire-speed ingest lane
(ISSUE 18 tentpole (a)).

The JSON front door parses op dicts and re-encodes them server-side on
every request; per-request CPU is JSON-dominated (ROADMAP open item 3).
PR 15 made `history.packing.encode_history` columnar, deterministic,
and byte-identical across hosts — which makes encoding *relocatable*:
a client can run the same pure encode locally and ship the packed int32
tensors instead of the op dicts. This module is the wire format for
that: a length-delimited binary frame holding a small JSON header (the
routing metadata: workload/algorithm/consistency, per-unit shapes) and
the raw little-endian int32 buffers of each unit's `EncodedHistory`
(events, op_index, and the proc array the weak rungs hash), closed by a
CRC32.

Layout (all integers little-endian)::

    offset 0   magic      4 bytes   b"JGF1"
    offset 4   kind       uint16    1 = submit, 2 = stream segment
    offset 6   reserved   uint16    0
    offset 8   header_len uint32    H
    offset 12  header     H bytes   canonical JSON (sort_keys, compact)
    …          pad        0–7 zero bytes to 8-align the buffers
    …          buffers    per unit, in header order:
                            events   [n_events, 5] int32
                            op_index [n_events]    int32
                            proc     [n_events]    int32 (when present)
    tail       crc        uint32    CRC32 over every preceding byte

Decoding is ZERO-COPY: `np.frombuffer` slices each buffer straight out
of the received bytes (read-only views — nothing downstream mutates an
encoding), so the only per-request tensor work left on the server is
the sha256 fingerprint — which the server ALWAYS re-derives over the
received bytes (service/request.admit_encoded). A client-claimed
fingerprint is advisory: a lying client corrupts only its own verdict,
because every cache/store/WAL key is the server-derived digest
(doc/checker-design.md §20).

Malformed input (bad magic, truncated/torn frame, CRC rot, header/
buffer disagreement) raises `FrameError` — a ValueError the HTTP
surface maps to 400, never a crash or a silently mis-sliced tensor.
"""

from __future__ import annotations

import json
import struct
import zlib
from dataclasses import dataclass
from typing import List, Optional, Sequence

import numpy as np

from ..history.packing import EncodedHistory

#: Frame magic + format version (the "1" is the version).
MAGIC = b"JGF1"

#: `kind` field values.
KIND_SUBMIT = 1
KIND_STREAM_SEG = 2

#: Fixed prefix: magic, kind, reserved, header_len.
_PREFIX = struct.Struct("<4sHHI")

#: Little-endian int32 — the one dtype on the wire, pinned explicitly
#: so a big-endian host still produces/reads identical frames.
_I32 = np.dtype("<i4")

#: Header size cap: routing metadata is a few hundred bytes; a
#: multi-megabyte "header" is a malformed (or hostile) frame.
MAX_HEADER_BYTES = 1 << 20


class FrameError(ValueError):
    """Malformed binary frame (HTTP 400 at the service surface)."""


@dataclass
class SubmitFrame:
    """Decoded submission frame: admission metadata plus the per-unit
    encodings, views over the received bytes."""

    workload: str
    algorithm: str
    consistency: str
    labels: List[str]
    encs: List[EncodedHistory]
    deadline_ms: Optional[float] = None
    priority: int = 0
    #: client-claimed fingerprint (advisory; the server re-derives).
    fingerprint: Optional[str] = None  # lint: allow(fp-irrelevant) advisory claim; server-derived digest is the key


@dataclass
class SegmentFrame:
    """Decoded stream-segment frame: one settled-suffix append (ISSUE
    18 tentpole (b)). `units` entries carry the suffix arrays plus the
    client encoder's cumulative counters (the server runs no encoder on
    the binary lane)."""

    session: str
    seq: int
    units: List[dict]


def _pad(n: int) -> int:
    """Zero bytes after an n-byte header to 8-align the buffers."""
    return -n % 8


def _header_and_buffers(kind: int, header: dict,
                        buffers: Sequence[np.ndarray]) -> bytes:
    hdr = json.dumps(header, sort_keys=True,
                     separators=(",", ":")).encode()
    parts = [_PREFIX.pack(MAGIC, kind, 0, len(hdr)), hdr,
             b"\x00" * _pad(len(hdr))]
    for arr in buffers:
        parts.append(np.ascontiguousarray(arr, dtype=_I32).tobytes())
    body = b"".join(parts)
    return body + struct.pack("<I", zlib.crc32(body))


def _unit_meta(enc: EncodedHistory) -> dict:
    return {
        "n_events": int(enc.events.shape[0]),
        "n_slots": int(enc.n_slots),
        "n_ops": int(enc.n_ops),
        "proc": enc.proc is not None,
    }


def _unit_buffers(enc: EncodedHistory) -> List[np.ndarray]:
    bufs = [enc.events, enc.op_index]
    if enc.proc is not None:
        bufs.append(enc.proc)
    return bufs


def encode_submit_frame(workload: str, algorithm: str, consistency: str,
                        labels: Sequence[str],
                        encs: Sequence[EncodedHistory],
                        deadline_ms: Optional[float] = None,
                        priority: int = 0,
                        fingerprint: Optional[str] = None) -> bytes:
    """Pack an admitted submission (the client-side `encode_history`
    output) into one submit frame."""
    if len(labels) != len(encs):
        raise FrameError(f"{len(labels)} labels for {len(encs)} "
                         "encodings")
    header = {
        "workload": str(workload),
        "algorithm": str(algorithm),
        "consistency": str(consistency),
        "priority": int(priority),
        "units": [dict(_unit_meta(e), label=str(lab))
                  for lab, e in zip(labels, encs)],
    }
    if deadline_ms is not None:
        header["deadline_ms"] = float(deadline_ms)
    if fingerprint is not None:
        header["fingerprint"] = str(fingerprint)
    buffers: List[np.ndarray] = []
    for e in encs:
        buffers.extend(_unit_buffers(e))
    return _header_and_buffers(KIND_SUBMIT, header, buffers)


def encode_segment_frame(session: str, seq: int,
                         units: Sequence[dict]) -> bytes:
    """Pack one binary stream segment: per unit, the newly settled
    suffix arrays (`IncrementalEncoder.feed` output) plus the client
    encoder's cumulative counters after this segment. Unit dicts:
    ``{"events", "op_index", "proc" (array or None), "n_slots",
    "n_ops", "consumed", "final"}``."""
    meta = []
    buffers: List[np.ndarray] = []
    for u in units:
        ev = np.ascontiguousarray(u["events"], dtype=_I32).reshape(-1, 5)
        oi = np.ascontiguousarray(u["op_index"], dtype=_I32)
        pr = u.get("proc")
        meta.append({
            "n_events": int(ev.shape[0]),
            "n_slots": int(u["n_slots"]),
            "n_ops": int(u["n_ops"]),
            "consumed": int(u["consumed"]),
            "final": bool(u.get("final", False)),
            "proc": pr is not None,
        })
        buffers.append(ev)
        buffers.append(oi)
        if pr is not None:
            buffers.append(np.ascontiguousarray(pr, dtype=_I32))
    header = {"session": str(session), "seq": int(seq), "units": meta}
    return _header_and_buffers(KIND_STREAM_SEG, header, buffers)


def _take(mv: memoryview, offset: int, n_i32: int, total: int):
    """Zero-copy int32 view of `n_i32` little-endian words at `offset`;
    bounds-checked against the buffer region so a lying header is a
    FrameError, never a mis-sliced tensor."""
    end = offset + 4 * n_i32
    if end > total:
        raise FrameError(f"buffer region truncated (need {end} bytes, "
                         f"frame carries {total})")
    return np.frombuffer(mv[offset:end], dtype=_I32), end


def _decode_units(mv: memoryview, offset: int, total: int, metas,
                  want: tuple) -> List[dict]:
    """Shared buffer walk: per unit meta, slice events/op_index[/proc]
    and carry the `want` counter fields through."""
    out: List[dict] = []
    for i, m in enumerate(metas):
        if not isinstance(m, dict):
            raise FrameError(f"unit {i} metadata is not an object")
        try:
            n_ev = int(m["n_events"])
            has_proc = bool(m["proc"])
            fields = {k: t(m[k]) for k, t in want}
        except (KeyError, TypeError, ValueError) as e:
            raise FrameError(f"unit {i} metadata malformed: {e}") from None
        if n_ev < 0:
            raise FrameError(f"unit {i}: negative n_events")
        flat, offset = _take(mv, offset, n_ev * 5, total)
        events = flat.reshape(n_ev, 5)
        op_index, offset = _take(mv, offset, n_ev, total)
        proc = None
        if has_proc:
            proc, offset = _take(mv, offset, n_ev, total)
        out.append(dict(fields, events=events, op_index=op_index,
                        proc=proc))
    if offset != total:
        raise FrameError(f"{total - offset} trailing byte(s) after the "
                         "last declared buffer")
    return out


def decode_frame(buf):
    """Decode one frame → `SubmitFrame` | `SegmentFrame`. Raises
    `FrameError` on anything malformed: bad magic, unknown kind/
    version, truncation anywhere, CRC mismatch, or a header whose
    declared shapes disagree with the bytes actually present."""
    mv = memoryview(buf)
    if len(mv) < _PREFIX.size + 4:
        raise FrameError(f"frame too short ({len(mv)} bytes)")
    magic, kind, _reserved, hdr_len = _PREFIX.unpack_from(mv, 0)
    if magic != MAGIC:
        raise FrameError(f"bad magic {bytes(magic)!r} "
                         f"(expected {MAGIC!r})")
    if hdr_len > MAX_HEADER_BYTES:
        raise FrameError(f"header length {hdr_len} over the "
                         f"{MAX_HEADER_BYTES}-byte cap")
    (crc,) = struct.unpack_from("<I", mv, len(mv) - 4)
    if zlib.crc32(mv[:len(mv) - 4]) != crc:
        raise FrameError("frame CRC mismatch (torn or corrupted)")
    hdr_end = _PREFIX.size + hdr_len
    body_start = hdr_end + _pad(hdr_len)
    if body_start > len(mv) - 4:
        raise FrameError("header overruns the frame")
    try:
        header = json.loads(bytes(mv[_PREFIX.size:hdr_end]))
        if not isinstance(header, dict):
            raise ValueError("header is not a JSON object")
    except (ValueError, json.JSONDecodeError) as e:
        raise FrameError(f"bad frame header: {e}") from None
    metas = header.get("units")
    if not isinstance(metas, list) or not metas:
        raise FrameError("frame header carries no units")
    total = len(mv) - 4
    if kind == KIND_SUBMIT:
        units = _decode_units(mv, body_start, total, metas,
                              want=(("label", str), ("n_slots", int),
                                    ("n_ops", int)))
        ddl = header.get("deadline_ms")
        fp = header.get("fingerprint")
        try:
            return SubmitFrame(
                workload=str(header["workload"]),
                algorithm=str(header.get("algorithm", "auto")),
                consistency=str(header.get("consistency",
                                           "linearizable")),
                labels=[u["label"] for u in units],
                encs=[EncodedHistory(events=u["events"],
                                     op_index=u["op_index"],
                                     n_slots=u["n_slots"],
                                     n_ops=u["n_ops"],
                                     proc=u["proc"])
                      for u in units],
                deadline_ms=float(ddl) if ddl is not None else None,
                priority=int(header.get("priority", 0)),
                fingerprint=str(fp) if fp is not None else None)
        except (KeyError, TypeError, ValueError) as e:
            raise FrameError(f"bad submit header: {e}") from None
    if kind == KIND_STREAM_SEG:
        units = _decode_units(mv, body_start, total, metas,
                              want=(("n_slots", int), ("n_ops", int),
                                    ("consumed", int), ("final", bool)))
        try:
            return SegmentFrame(session=str(header["session"]),
                                seq=int(header["seq"]), units=units)
        except (KeyError, TypeError, ValueError) as e:
            raise FrameError(f"bad segment header: {e}") from None
    raise FrameError(f"unknown frame kind {kind}")

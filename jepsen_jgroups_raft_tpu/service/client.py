"""Thin HTTP client for graftd — stdlib http.client, JSON in/out.

The tenant-side counterpart of service/http.py: tests, the bench's
--service throughput mode, and any external submitter use this instead
of hand-rolling requests. One connection per call (the daemon is
ThreadingHTTPServer; connection reuse buys nothing at this scale and a
stateless client survives daemon restarts for free).
"""

from __future__ import annotations

import json
import time
from http.client import HTTPConnection
from typing import Optional, Sequence


class ServiceError(Exception):
    """Non-2xx daemon answer. `status` is the HTTP code; `payload` the
    decoded JSON body (carries `retry_after_s` on 429)."""

    def __init__(self, status: int, payload: dict):
        super().__init__(f"HTTP {status}: {payload.get('error', payload)}")
        self.status = status
        self.payload = payload

    @property
    def retry_after_s(self) -> Optional[float]:
        v = self.payload.get("retry_after_s")
        return float(v) if v is not None else None


class ServiceClient:
    def __init__(self, base_url: str, timeout: float = 30.0):
        # base_url: http://host:port (path prefixes unsupported — the
        # daemon serves at the root, like core/serve.py).
        if "://" in base_url:
            base_url = base_url.split("://", 1)[1]
        self.netloc = base_url.rstrip("/")
        self.timeout = timeout

    def _call(self, method: str, path: str,
              body: Optional[dict] = None) -> dict:
        conn = HTTPConnection(self.netloc, timeout=self.timeout)
        try:
            payload = json.dumps(body).encode() if body is not None else None
            headers = {"Content-Type": "application/json"} if payload else {}
            conn.request(method, path, body=payload, headers=headers)
            resp = conn.getresponse()
            data = json.loads(resp.read() or b"{}")
        finally:
            conn.close()
        if resp.status >= 400:
            raise ServiceError(resp.status, data)
        return data

    # ------------------------------------------------------- surface

    def submit(self, histories: Sequence, workload: str = "register",
               algorithm: str = "auto", deadline_ms: Optional[float] = None,
               priority: int = 0) -> dict:
        """Submit histories (History objects or op-dict lists); returns
        the daemon's request record ({"id", "status", ...}). Raises
        ServiceError on 429 (read `.retry_after_s`) or 400."""
        rows = [h.to_dicts() if hasattr(h, "to_dicts") else list(h)
                for h in histories]
        return self._call("POST", "/submit", {
            "workload": workload, "histories": rows,
            "algorithm": algorithm, "deadline_ms": deadline_ms,
            "priority": priority})

    def submit_run_dir(self, run_dir: str, workload: Optional[str] = None,
                       algorithm: str = "auto") -> dict:
        return self._call("POST", "/submit", {
            "run_dir": str(run_dir), "workload": workload,
            "algorithm": algorithm})

    def result(self, request_id: str,
               wait_s: Optional[float] = None) -> dict:
        path = f"/result?id={request_id}"
        if wait_s is not None:
            path += f"&wait_s={wait_s}"
        return self._call("GET", path)

    def cancel(self, request_id: str) -> dict:
        return self._call("POST", "/cancel", {"id": request_id})

    def stats(self) -> dict:
        return self._call("GET", "/stats")

    def healthz(self) -> dict:
        return self._call("GET", "/healthz")

    def check(self, histories: Sequence, workload: str = "register",
              algorithm: str = "auto", timeout_s: float = 300.0,
              poll_s: float = 0.05) -> dict:
        """Submit-and-wait convenience: returns the terminal request
        record (results included). Waits server-side in bounded slices
        so one slow verdict cannot park the connection past the
        daemon's handler cap."""
        rec = self.submit(histories, workload=workload, algorithm=algorithm)
        if rec.get("status") in ("done", "failed", "cancelled"):
            return self.result(rec["id"])
        deadline = time.monotonic() + timeout_s
        while True:
            rec = self.result(rec["id"], wait_s=min(
                10.0, max(poll_s, deadline - time.monotonic())))
            if rec.get("status") in ("done", "failed", "cancelled"):
                return rec
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"request {rec['id']} still {rec.get('status')} after "
                    f"{timeout_s:.0f}s")

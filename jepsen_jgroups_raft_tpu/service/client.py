"""Thin HTTP client for graftd — stdlib http.client, JSON in/out.

The tenant-side counterpart of service/http.py: tests, the bench's
--service throughput mode, and any external submitter use this instead
of hand-rolling requests.

Connection reuse (ISSUE 18 satellite): calls keep-alive their
connection per (thread, replica) and reuse it across submits, polls,
and retries — at wire-speed ingest rates the TCP handshake per call is
a measurable tax (the `conn_opened`/`conn_reused` counters are the
bench's A/B evidence). The daemon speaks HTTP/1.1 persistent
connections already; a STALE kept-alive socket (daemon restarted
between calls) is retried once on a fresh connection without consuming
the caller's attempt budget, so restart-survival is as good as the old
connection-per-call stance. ``JGRAFT_CLIENT_KEEPALIVE=0`` restores
that stance exactly (and is the bench's other arm).

Binary ingest (ISSUE 18 tentpole): ``submit(..., binary=True)`` runs
the pure `encode_history` LOCALLY and ships the packed int32 tensors
as one `service/frame.py` columnar frame — no JSON op serialization,
no server-side encode. `stream(..., binary=True)` does the same
per-segment with a client-owned `IncrementalEncoder`. The server
re-derives the fingerprint over the received bytes either way, so a
corrupt client harms only its own verdict. Same-host producers can
point `base_url` at ``unix:/path/to/graftd.sock`` (the daemon's
JGRAFT_SERVICE_UDS listener) and skip the TCP stack entirely.

Retry discipline (ISSUE 8): submission is IDEMPOTENT server-side — a
resubmitted fingerprint attaches to the live request or hits the result
cache instead of double-checking — so the client can safely retry the
failure modes a durable daemon actually produces: 429 backpressure
(honoring the daemon's Retry-After), 503 while a restart is in flight
(same), and connection-level failures (daemon SIGKILL'd mid-call; the
request may or may not have been journaled — resubmitting is safe
either way, which is the whole point of idempotency). Backoff is capped
exponential with full jitter and a max-attempts cap; callers that want
the old single-shot behavior pass ``max_attempts=1``.

Cluster routing (ISSUE 11): pass ``replicas=[url…]`` and the client
routes across the fleet — affinity-first (rendezvous hash over the
submission payload, so identical resubmissions land on the replica
whose caches already hold the verdict), least-loaded fallback (a
replica that refused or failed is deprioritized until its advertised
retry-after elapses), and failover retry that is safe because
submission is idempotent and verdicts live in the shared store. Two
rules are deliberately CLUSTER-GLOBAL, not per replica: ``max_attempts``
caps the total tries across all replicas (N replicas must not multiply
the retry budget into a fleet-wide storm), and a Retry-After is a floor
across replicas (a shedding replica answers with the CLUSTER's best
hint, so hopping to the next replica before it elapses just burns an
attempt on the same full cluster). A dead replica's connection error,
by contrast, fails over to the next replica immediately — liveness
probing is not load backoff. ``/result`` fails over on 404 too: after a
journal handoff the request lives on the surviving replica that
adopted it.
"""

from __future__ import annotations

import hashlib
import json
import random
import socket
import threading
import time
from collections import OrderedDict
from http.client import HTTPConnection, HTTPException
from typing import List, Optional, Sequence

from ..platform import env_int

#: Connection-level failures safe to retry once submission is
#: idempotent (refused/reset/timeout — the daemon-restart signatures).
RETRYABLE_CONN_ERRORS = (ConnectionError, HTTPException, TimeoutError,
                         OSError)

#: HTTP statuses that carry a retry_after_s hint and mean "try later".
RETRYABLE_STATUSES = (429, 503)

#: Content-Type of binary columnar frames (mirrors service/http.py —
#: not imported: the client must stay importable without dragging the
#: daemon stack in).
FRAME_CONTENT_TYPE = "application/x-jgraft-frame"


def client_keepalive() -> bool:
    """JGRAFT_CLIENT_KEEPALIVE gate (default on; 0 restores the
    connection-per-call client — the bench's A/B arm)."""
    return env_int("JGRAFT_CLIENT_KEEPALIVE", 1, minimum=0) != 0


class _UDSConnection(HTTPConnection):
    """http.client over an AF_UNIX socket — the client half of the
    daemon's same-host lane (ISSUE 18; `service/http.py`
    `_UnixHTTPServer`). The Host header is a dummy: HTTP routing over
    a unix socket is by path, not name."""

    def __init__(self, path: str, timeout=None):
        super().__init__("localhost", timeout=timeout)
        self._uds_path = path

    def connect(self):
        self.sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        if self.timeout is not None:
            self.sock.settimeout(self.timeout)
        self.sock.connect(self._uds_path)


class ServiceError(Exception):
    """Non-2xx daemon answer. `status` is the HTTP code; `payload` the
    decoded JSON body (carries `retry_after_s` on 429 AND 503)."""

    def __init__(self, status: int, payload: dict):
        super().__init__(f"HTTP {status}: {payload.get('error', payload)}")
        self.status = status
        self.payload = payload

    @property
    def retry_after_s(self) -> Optional[float]:
        v = self.payload.get("retry_after_s")
        return float(v) if v is not None else None


def backoff_delay(attempt: int, base_s: float, cap_s: float,
                  retry_after_s: Optional[float] = None,
                  rng: Optional[random.Random] = None) -> float:
    """Delay before retry `attempt` (1-based): capped exponential with
    FULL jitter — `uniform(0, min(cap, base·2^(attempt-1)))` — so a
    retry storm from many clients decorrelates instead of re-arriving
    in lockstep. A server-provided Retry-After is a floor, not a
    suggestion: we never come back EARLIER than the daemon asked, and
    jitter is added on top (still capped) so even Retry-After herds
    spread out."""
    r = (rng or random).uniform(0.0, 1.0)
    exp = min(cap_s, base_s * (2.0 ** max(0, attempt - 1)))
    delay = r * exp
    if retry_after_s is not None:
        delay = min(retry_after_s + r * exp, retry_after_s + cap_s)
        delay = max(delay, retry_after_s)
    return delay


def _close_quietly(conn: HTTPConnection) -> None:
    try:
        conn.close()
    except OSError:
        pass  # already dead — closing was the point


def _netloc(url: str) -> str:
    if url.startswith("unix:"):
        # same-host lane: "unix:/abs/path/to/graftd.sock". The path is
        # carried in the netloc verbatim behind the "unix:" sentinel.
        return "unix:" + url[len("unix:"):]
    if "://" in url:
        url = url.split("://", 1)[1]
    return url.rstrip("/")


class ServiceClient:
    def __init__(self, base_url: str, timeout: float = 30.0,
                 max_attempts: int = 4, backoff_base_s: float = 0.1,
                 backoff_cap_s: float = 5.0,
                 rng: Optional[random.Random] = None,
                 replicas: Optional[Sequence[str]] = None):
        # base_url: http://host:port (path prefixes unsupported — the
        # daemon serves at the root, like core/serve.py). `replicas`
        # (ISSUE 11) adds the rest of the cluster; base_url's replica
        # is included automatically and single-URL behavior is
        # byte-for-byte unchanged when it is omitted.
        self.netloc = _netloc(base_url)
        self.netlocs: List[str] = [self.netloc]
        for u in replicas or ():
            n = _netloc(u)
            if n not in self.netlocs:
                self.netlocs.append(n)
        self.timeout = timeout
        self.max_attempts = max(1, int(max_attempts))
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self._rng = rng or random.Random()
        #: wall time before which each replica is deprioritized in the
        #: fallback order (stamped from its Retry-After / failures) —
        #: the client-side half of least-loaded routing.
        self._penalty_until: dict = {}
        #: cluster-wide Retry-After floor (module docstring).
        self._floor_until = 0.0
        #: connection-level failovers performed (a replica died and the
        #: call moved on) — the bench's failover-latency evidence.
        self.failovers = 0
        #: request id → the replica that answered for it (bounded):
        #: result/cancel polls go straight to the owner instead of
        #: walking 404 probes across the fleet on every poll. A stale
        #: or lost hint only costs probes, never correctness.
        self._owner: "OrderedDict[str, str]" = OrderedDict()
        #: netloc that served the most recent successful _call (feeds
        #: the owner map; best-effort under concurrent use).
        self._answered_by: Optional[str] = None
        #: per-THREAD keep-alive pool, netloc → live HTTPConnection.
        #: Thread-local because http.client connections are not
        #: thread-safe and the bench drives one client from many
        #: submitter threads.
        self._local = threading.local()
        self._counter_lock = threading.Lock()
        #: keep-alive A/B evidence (ISSUE 18 satellite): sockets dialed
        #: vs. calls served on an already-open connection.
        self.conn_opened = 0
        self.conn_reused = 0

    # ---------------------------------------------------- connections

    def _connect(self, netloc: str) -> HTTPConnection:
        if netloc.startswith("unix:"):
            return _UDSConnection(netloc[len("unix:"):],
                                  timeout=self.timeout)
        return HTTPConnection(netloc, timeout=self.timeout)

    def _pool(self) -> dict:
        pool = getattr(self._local, "pool", None)
        if pool is None:
            pool = self._local.pool = {}
        return pool

    def _checkout(self, netloc: str, force_fresh: bool = False):
        """(connection, was_reused) for one call. Reuse comes from this
        thread's pool; `force_fresh` bypasses it (the stale-keep-alive
        retry)."""
        if client_keepalive() and not force_fresh:
            conn = self._pool().pop(netloc, None)
            if conn is not None:
                with self._counter_lock:
                    self.conn_reused += 1
                return conn, True
        with self._counter_lock:
            self.conn_opened += 1
        return self._connect(netloc), False

    def _checkin(self, netloc: str, conn: HTTPConnection) -> None:
        pool = self._pool()
        old = pool.get(netloc)
        if old is not None and old is not conn:
            _close_quietly(old)
        pool[netloc] = conn

    def close(self) -> None:
        """Drop this THREAD's kept-alive connections (worker teardown
        hygiene; other threads' pools drain when their thread dies)."""
        pool = getattr(self._local, "pool", None) or {}
        for conn in pool.values():
            _close_quietly(conn)
        pool.clear()

    # ------------------------------------------------------- routing

    def _route(self, affinity: Optional[str] = None,
               prefer: Optional[str] = None) -> List[str]:
        """Replica order for one logical call: `prefer` (the known
        owner of the id being polled) first when given, else the affine
        replica (rendezvous hash — stable per payload, uniform across
        fingerprints), then the rest least-loaded-first (soonest
        penalty expiry; ties keep the configured order)."""
        if len(self.netlocs) == 1:
            return list(self.netlocs)
        if affinity:
            # ONE digest construction per route (ISSUE 15 satellite):
            # the affinity prefix is hashed once and each replica's
            # rendezvous key extends a cheap .copy() of that state —
            # byte-identical to sha256(f"{affinity}|{n}") (same input
            # stream), so the route order is unchanged, but the
            # per-replica rehash of the (payload-sized) key is gone.
            hd = hashlib.sha256(affinity.encode())

            def rendezvous(n: str) -> str:
                h = hd.copy()
                h.update(f"|{n}".encode())
                return h.hexdigest()

            ordered = sorted(self.netlocs, key=rendezvous, reverse=True)
        else:
            ordered = list(self.netlocs)
        now = time.monotonic()
        head, tail = ordered[:1], ordered[1:]
        tail.sort(key=lambda n: max(0.0,
                                    self._penalty_until.get(n, 0.0) - now))
        route = head + tail
        if prefer in self.netlocs and route[0] != prefer:
            route.remove(prefer)
            route.insert(0, prefer)
        return route

    def _remember_owner(self, request_id: Optional[str]) -> None:
        if not request_id or self._answered_by is None \
                or len(self.netlocs) == 1:
            return
        self._owner[request_id] = self._answered_by
        self._owner.move_to_end(request_id)
        while len(self._owner) > 1024:
            self._owner.popitem(last=False)

    def _penalize(self, netloc: str, for_s: float) -> None:
        self._penalty_until[netloc] = max(
            self._penalty_until.get(netloc, 0.0),
            time.monotonic() + max(0.1, for_s))

    def _call_once(self, method: str, path: str,
                   body: Optional[dict] = None,
                   netloc: Optional[str] = None,
                   raw: Optional[bytes] = None,
                   content_type: Optional[str] = None) -> dict:
        netloc = netloc or self.netloc
        if raw is not None:
            payload: Optional[bytes] = raw
            headers = {"Content-Type":
                       content_type or FRAME_CONTENT_TYPE}
        elif body is not None:
            payload = json.dumps(body).encode()
            headers = {"Content-Type": "application/json"}
        else:
            payload, headers = None, {}
        for fresh in (False, True):
            conn, reused = self._checkout(netloc, force_fresh=fresh)
            try:
                conn.request(method, path, body=payload, headers=headers)
                resp = conn.getresponse()
                data = json.loads(resp.read() or b"{}")
            except RETRYABLE_CONN_ERRORS:
                _close_quietly(conn)
                if reused and not fresh:
                    # A REUSED socket died mid-call: the classic stale
                    # keep-alive race (daemon restarted / idle-closed
                    # between calls). One immediate fresh-connection
                    # retry, NOT charged to the caller's attempt budget
                    # — this failure mode is an artifact of reuse, and
                    # without this the keep-alive client would be
                    # strictly less robust than connection-per-call.
                    continue
                raise
            if resp.will_close or not client_keepalive():
                _close_quietly(conn)
            else:
                self._checkin(netloc, conn)
            if resp.status >= 400:
                raise ServiceError(resp.status, data)
            return data
        raise AssertionError("unreachable")  # loop returns or raises

    def _call(self, method: str, path: str, body: Optional[dict] = None,
              retry: bool = True, affinity: Optional[str] = None,
              failover_404: bool = False,
              prefer: Optional[str] = None,
              raw: Optional[bytes] = None,
              content_type: Optional[str] = None) -> dict:
        """One logical call with the retry discipline (module
        docstring). `retry=False` restores single-shot semantics for
        calls the caller wants to fail fast. The attempt cap is
        CLUSTER-GLOBAL: every try, on whichever replica, counts against
        the same `max_attempts` budget — failover must not multiply
        the retry storm by the replica count (ISSUE 11 satellite)."""
        route = self._route(affinity, prefer=prefer)
        attempts = self.max_attempts if retry else 1
        last: Exception = None
        ri = 0
        seen_404 = 0
        attempt = 0
        while attempt < attempts:
            attempt += 1
            netloc = route[ri % len(route)]
            # binary-frame kwargs only when in play: JSON calls keep the
            # historical _call_once shape (test transports stub it)
            extra = ({"raw": raw, "content_type": content_type}
                     if raw is not None or content_type is not None else {})
            try:
                out = self._call_once(method, path, body, netloc=netloc,
                                      **extra)
                self._penalty_until.pop(netloc, None)
                self._answered_by = netloc
                return out
            except ServiceError as e:
                if e.status == 404 and failover_404 \
                        and seen_404 < len(route) - 1:
                    # the request may live on the replica that adopted
                    # a dead peer's journal: probe the rest of the
                    # fleet before concluding "unknown id". Probes are
                    # sequential reads, not retries — they do not
                    # consume the attempt budget.
                    attempt -= 1
                    seen_404 += 1
                    ri += 1
                    continue
                if e.status not in RETRYABLE_STATUSES \
                        or attempt >= attempts:
                    raise
                last = e
                if e.retry_after_s is not None:
                    # the daemon's hint is already the CLUSTER's best
                    # (its 429 consults peer leases): floor every
                    # replica behind it, not just the one that answered
                    self._floor_until = max(
                        self._floor_until,
                        time.monotonic() + e.retry_after_s)
                    self._penalize(netloc, e.retry_after_s)
                delay = backoff_delay(attempt, self.backoff_base_s,
                                      self.backoff_cap_s,
                                      retry_after_s=e.retry_after_s,
                                      rng=self._rng)
                delay = max(delay, self._floor_until - time.monotonic())
                ri += 1
            except RETRYABLE_CONN_ERRORS as e:
                # Safe because /submit is idempotent (fingerprint
                # attach / cache hit) and every other endpoint is a
                # read or an idempotent cancel.
                if attempt >= attempts:
                    raise
                last = e
                self._penalize(netloc, 1.0)
                ri += 1
                if len(route) > 1 and attempt < len(route):
                    # a dead replica is a liveness event, not load:
                    # fail over to the next replica immediately
                    self.failovers += 1
                    continue
                delay = backoff_delay(attempt, self.backoff_base_s,
                                      self.backoff_cap_s, rng=self._rng)
            time.sleep(max(0.0, delay))
        raise last  # unreachable; loop always returns or raises

    # ------------------------------------------------------- surface

    def submit(self, histories: Sequence, workload: str = "register",
               algorithm: str = "auto", deadline_ms: Optional[float] = None,
               priority: int = 0, retry: bool = True,
               consistency: str = "linearizable",
               affinity: bool = True, binary: bool = False) -> dict:
        """Submit histories (History objects or op-dict lists); returns
        the daemon's request record ({"id", "status", ...}). Retries
        429/503/connection failures with capped jittered backoff up to
        `max_attempts` (safe: submission is idempotent); the final
        failure raises ServiceError (read `.retry_after_s`) or the
        connection error. `retry=False` fails fast. `consistency`
        selects the verdict's ladder rung (linearizable / sequential /
        session). `binary=True` encodes CLIENT-SIDE and ships one
        columnar frame (ISSUE 18) — same verdict, same idempotency
        (the server re-derives the fingerprint over the same bytes the
        JSON path would have encoded to)."""
        if binary:
            return self._submit_binary(
                histories, workload=workload, algorithm=algorithm,
                deadline_ms=deadline_ms, priority=priority, retry=retry,
                consistency=consistency, affinity=affinity)
        rows = [h.to_dicts() if hasattr(h, "to_dicts") else list(h)
                for h in histories]
        key = None
        if affinity and len(self.netlocs) > 1:
            # content-keyed affinity (ISSUE 11): identical payloads
            # route to the same replica, so idempotent resubmissions
            # attach/cache-hit there instead of fanning one fingerprint
            # across the fleet. Scheduling metadata (deadline,
            # priority) stays out of the key — it does not change the
            # verdict identity. `affinity=False` keeps the configured
            # replica order (the bench's failover phase pins the dead
            # replica at the head this way).
            key = hashlib.sha256(json.dumps(
                [workload, algorithm, consistency, rows],
                sort_keys=True, default=str).encode()).hexdigest()
        rec = self._call("POST", "/submit", {
            "workload": workload, "histories": rows,
            "algorithm": algorithm, "deadline_ms": deadline_ms,
            "priority": priority, "consistency": consistency},
            retry=retry, affinity=key)
        self._remember_owner(rec.get("id"))
        return rec

    def _submit_binary(self, histories: Sequence, workload: str,
                       algorithm: str, deadline_ms: Optional[float],
                       priority: int, retry: bool, consistency: str,
                       affinity: bool) -> dict:
        """Client-side encode + one columnar frame (ISSUE 18 tentpole):
        the SAME `build_units` + `encode_history` the server's JSON
        path runs, executed here — so the server-derived fingerprint
        over the shipped tensors is byte-identical to the JSON path's,
        and the locally computed digest doubles as the rendezvous
        affinity key (replica cache locality for free). The frame is
        built ONCE; every retry re-sends identical bytes."""
        from ..checker.consistency import normalize_consistency
        from .frame import encode_submit_frame
        from .request import build_units, fingerprint_encodings

        from ..history.packing import encode_history

        consistency = normalize_consistency(consistency)
        model, units = build_units(histories, workload)
        encs = [encode_history(h, model) for _, h in units]
        fp = fingerprint_encodings(model, algorithm, encs, consistency)
        frame = encode_submit_frame(
            workload, algorithm, consistency,
            [label for label, _ in units], encs,
            deadline_ms=deadline_ms, priority=priority, fingerprint=fp)
        rec = self._call("POST", "/submit", retry=retry,
                         affinity=fp if affinity else None, raw=frame)
        self._remember_owner(rec.get("id"))
        return rec

    def submit_run_dir(self, run_dir: str, workload: Optional[str] = None,
                       algorithm: str = "auto", retry: bool = True,
                       consistency: str = "linearizable") -> dict:
        return self._call("POST", "/submit", {
            "run_dir": str(run_dir), "workload": workload,
            "algorithm": algorithm, "consistency": consistency},
            retry=retry)

    def result(self, request_id: str,
               wait_s: Optional[float] = None) -> dict:
        path = f"/result?id={request_id}"
        if wait_s is not None:
            path += f"&wait_s={wait_s}"
        # The known owner (the replica that answered the submit or the
        # last poll) leads the route — polling must not walk 404
        # probes across the fleet on every call. 404 still fails over:
        # after a journal handoff the id answers from the survivor
        # that adopted it (ISSUE 11), and the owner map re-learns it.
        rec = self._call("GET", path,
                         failover_404=len(self.netlocs) > 1,
                         prefer=self._owner.get(request_id))
        self._remember_owner(request_id)
        return rec

    def cancel(self, request_id: str) -> dict:
        return self._call("POST", "/cancel", {"id": request_id},
                          prefer=self._owner.get(request_id))

    def stats(self) -> dict:
        return self._call("GET", "/stats")

    def healthz(self) -> dict:
        return self._call("GET", "/healthz")

    def stream(self, workload: str = "register", units: int = 1,
               algorithm: str = "auto",
               consistency: str = "linearizable",
               session_id: Optional[str] = None,
               resume: bool = False,
               binary: bool = False) -> "StreamSession":
        """Open (or resume) a streaming verdict session (ISSUE 12);
        returns a `StreamSession` whose `append`/`finish` carry the
        per-segment idempotent retry discipline. `binary=True` runs
        the incremental encoder CLIENT-side and ships each settled
        suffix as a columnar frame (ISSUE 18)."""
        s = StreamSession(self, workload=workload, units=units,
                          algorithm=algorithm, consistency=consistency,
                          session_id=session_id, resume=resume,
                          binary=binary)
        s.open()
        return s

    def check(self, histories: Sequence, workload: str = "register",
              algorithm: str = "auto", timeout_s: float = 300.0,
              poll_s: float = 0.05,
              consistency: str = "linearizable") -> dict:
        """Submit-and-wait convenience: returns the terminal request
        record (results included). Waits server-side in bounded slices
        so one slow verdict cannot park the connection past the
        daemon's handler cap."""
        rec = self.submit(histories, workload=workload, algorithm=algorithm,
                          consistency=consistency)
        if rec.get("status") in ("done", "failed", "cancelled"):
            return self.result(rec["id"])
        deadline = time.monotonic() + timeout_s
        while True:
            rec = self.result(rec["id"], wait_s=min(
                10.0, max(poll_s, deadline - time.monotonic())))
            if rec.get("status") in ("done", "failed", "cancelled"):
                return rec
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"request {rec['id']} still {rec.get('status')} after "
                    f"{timeout_s:.0f}s")


class StreamSession:
    """Producer-side streaming session (ISSUE 12 tentpole (d)).

    Wraps one server-side stream session: `open` / `append` / `finish`
    with the per-segment idempotent retry discipline. The client owns
    the sequence numbers; a segment whose response was lost (connection
    error, daemon SIGKILL mid-call) is simply RE-SENT under the same
    seq — the server's duplicate detection (payload digest) makes the
    retry a no-op when the first copy landed, and the WAL makes it
    durable when it did not. 429/503/connection retries ride the
    owning ServiceClient's backoff (idempotent by construction, so the
    same safety argument as /submit applies).

    A crashed PRODUCER is recoverable too: a fresh process constructs
    the session with ``session_id=<sid>, resume=True`` — the server
    answers with its current state (including ``next_seq``), and the
    new producer continues from there (the kill-the-client scenario in
    scripts/chaos_graftd.py).
    """

    def __init__(self, client: ServiceClient, workload: str = "register",
                 units: int = 1, algorithm: str = "auto",
                 consistency: str = "linearizable",
                 session_id: Optional[str] = None,
                 resume: bool = False, binary: bool = False):
        self.client = client
        self.workload = workload
        self.units = units
        self.algorithm = algorithm
        self.consistency = consistency
        self.session_id = session_id
        self.resume = resume
        #: binary lane (ISSUE 18): the incremental encoder runs HERE;
        #: each append ships the settled suffix as a columnar frame.
        #: Incompatible with `resume`: the encoder carry lives in this
        #: process, so a crashed binary producer cannot continue its
        #: old session (the JSON lane, whose encoder lives server-side,
        #: can) — it must open a fresh session instead.
        self.binary = binary
        if binary and resume:
            raise ValueError(
                "binary streams cannot resume: the client-side encoder "
                "carry died with the old producer; open a fresh "
                "session (or use the JSON lane, which resumes)")
        self._encoders: Optional[list] = None
        #: (seq, frame) whose send failed: re-sent (digest-idempotent)
        #: before the next append/finish, so a transport blip never
        #: desyncs the client encoder from the server's counters.
        self._pending_frame: Optional[tuple] = None
        self._finalized = False
        self.seq = 1
        self.last_state: Optional[dict] = None

    def open(self) -> dict:
        body = {"workload": self.workload, "units": self.units,
                "algorithm": self.algorithm,
                "consistency": self.consistency}
        if self.session_id:
            body["session"] = self.session_id
        if self.resume:
            body["resume"] = True
        rec = self.client._call("POST", "/stream/open", body)
        self.session_id = rec["session"]
        self.seq = int(rec.get("next_seq", 1))
        self.last_state = rec
        if self.binary and self._encoders is None:
            from ..history.packing import IncrementalEncoder
            from .request import service_workloads

            # the same model the server instantiated at open — the
            # client-side encoder must emit the stream the server-side
            # one would have (service_workloads is the shared registry)
            factory, _ = service_workloads()[self.workload]
            self._encoders = [IncrementalEncoder(factory())
                              for _ in range(int(self.units))]
        return rec

    @staticmethod
    def _rows(ops) -> list:
        if hasattr(ops, "to_dicts"):
            return ops.to_dicts()
        return [op.to_dict() if hasattr(op, "to_dict") else dict(op)
                for op in ops]

    def append(self, ops) -> dict:
        """Append one segment (a flat op list for single-unit sessions,
        or one list per unit). Assigns the next seq; safe to call again
        after any transport failure — the seq/digest pair makes the
        resend idempotent."""
        if self.binary:
            return self._append_binary(ops)
        if ops and not isinstance(ops[0], (list, tuple)) \
                or hasattr(ops, "to_dicts"):
            payload = self._rows(ops)
        elif ops and isinstance(ops[0], (list, tuple)):
            payload = [self._rows(u) for u in ops]
        else:
            payload = list(ops)
        seq = self.seq
        # An honest retry of a landed-but-unanswered segment re-sends
        # the IDENTICAL payload and gets 200 {duplicate: true} from the
        # digest check — so any 409 here is a REAL conflict (a second
        # producer on the same session, or a client bug) and must
        # surface, never be silently resynced past: swallowing it would
        # drop a segment the server explicitly refused to merge.
        rec = self.client._call("POST", "/stream/append", {
            "session": self.session_id, "seq": seq, "ops": payload})
        self.seq = seq + 1
        self.last_state = rec
        return rec

    # ----------------------------------------------------- binary lane

    def _parse_unit_ops(self, ops) -> list:
        """Wire-shape normalization for the binary lane, mirroring the
        server's `_parse_units` rules (flat list for single-unit
        sessions, one list per unit otherwise; nemesis rows filtered;
        list values retupled) — the client-side encoder must see
        exactly the rows the server-side one would have."""
        from ..history.ops import NEMESIS, Op

        if hasattr(ops, "to_dicts") or (
                ops and not isinstance(ops[0], (list, tuple))):
            per_unit = [list(ops)]
        elif ops:
            per_unit = [list(u) for u in ops]
        else:
            per_unit = [[] for _ in range(len(self._encoders))]
        if len(per_unit) != len(self._encoders):
            raise ValueError(
                f"segment carries {len(per_unit)} unit list(s); session "
                f"has {len(self._encoders)} unit(s)")
        parsed = []
        for rows in per_unit:
            out = []
            for d in rows:
                op = d if isinstance(d, Op) else Op.from_dict(dict(d))
                if isinstance(op.value, list):
                    op.value = tuple(op.value)
                if op.process != NEMESIS:
                    out.append(op)
            parsed.append(out)
        return parsed

    def _binary_payload(self, parsed, final: bool) -> list:
        units = []
        for encd, rows in zip(self._encoders, parsed):
            ev, oi, pr = encd.feed(rows, final=final)
            units.append({"events": ev, "op_index": oi, "proc": pr,
                          "n_slots": encd.n_slots, "n_ops": encd.n_ops,
                          "consumed": encd.consumed, "final": final})
        return units

    def _send_frame(self, seq: int, frame: bytes) -> dict:
        rec = self.client._call("POST", "/stream/append", raw=frame)
        self.seq = seq + 1
        self.last_state = rec
        return rec

    def _flush_pending(self) -> None:
        """Re-send a frame whose first send failed (digest-idempotent:
        identical bytes under the same seq). Without this a transport
        blip would desync the client encoder — which already consumed
        the ops — from the server's counters."""
        if self._pending_frame is None:
            return
        seq, frame = self._pending_frame
        self._send_frame(seq, frame)
        self._pending_frame = None

    def _append_binary(self, ops, final: bool = False) -> Optional[dict]:
        from .frame import encode_segment_frame

        self._flush_pending()
        parsed = self._parse_unit_ops(ops)
        # an empty final flush still ships: the segment carries the
        # final flag (and any end-of-history settle events)
        units = self._binary_payload(parsed, final=final)
        seq = self.seq
        frame = encode_segment_frame(self.session_id, seq, units)
        self._pending_frame = (seq, frame)
        rec = self._send_frame(seq, frame)
        self._pending_frame = None
        return rec

    # --------------------------------------------------------- surface

    def status(self) -> dict:
        rec = self.client._call(
            "GET", f"/stream/status?session={self.session_id}")
        self.last_state = rec
        return rec

    def finish(self) -> dict:
        if self.binary and not self._finalized:
            # the server REFUSES a binary finish without the final
            # flush (crashed-pair OPENs are linearization candidates);
            # send it exactly once — empty ops, final=true.
            self._append_binary([], final=True)
            self._finalized = True
        elif self.binary:
            self._flush_pending()
        rec = self.client._call("POST", "/stream/finish",
                                {"session": self.session_id})
        self.last_state = rec
        return rec

    @property
    def violation(self) -> Optional[dict]:
        """The first mid-run violation the daemon has surfaced, if
        any (from the most recent response)."""
        return (self.last_state or {}).get("violation")

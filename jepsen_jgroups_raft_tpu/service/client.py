"""Thin HTTP client for graftd — stdlib http.client, JSON in/out.

The tenant-side counterpart of service/http.py: tests, the bench's
--service throughput mode, and any external submitter use this instead
of hand-rolling requests. One connection per call (the daemon is
ThreadingHTTPServer; connection reuse buys nothing at this scale and a
stateless client survives daemon restarts for free).

Retry discipline (ISSUE 8): submission is IDEMPOTENT server-side — a
resubmitted fingerprint attaches to the live request or hits the result
cache instead of double-checking — so the client can safely retry the
failure modes a durable daemon actually produces: 429 backpressure
(honoring the daemon's Retry-After), 503 while a restart is in flight
(same), and connection-level failures (daemon SIGKILL'd mid-call; the
request may or may not have been journaled — resubmitting is safe
either way, which is the whole point of idempotency). Backoff is capped
exponential with full jitter and a max-attempts cap; callers that want
the old single-shot behavior pass ``max_attempts=1``.
"""

from __future__ import annotations

import json
import random
import time
from http.client import HTTPConnection, HTTPException
from typing import Optional, Sequence

#: Connection-level failures safe to retry once submission is
#: idempotent (refused/reset/timeout — the daemon-restart signatures).
RETRYABLE_CONN_ERRORS = (ConnectionError, HTTPException, TimeoutError,
                         OSError)

#: HTTP statuses that carry a retry_after_s hint and mean "try later".
RETRYABLE_STATUSES = (429, 503)


class ServiceError(Exception):
    """Non-2xx daemon answer. `status` is the HTTP code; `payload` the
    decoded JSON body (carries `retry_after_s` on 429 AND 503)."""

    def __init__(self, status: int, payload: dict):
        super().__init__(f"HTTP {status}: {payload.get('error', payload)}")
        self.status = status
        self.payload = payload

    @property
    def retry_after_s(self) -> Optional[float]:
        v = self.payload.get("retry_after_s")
        return float(v) if v is not None else None


def backoff_delay(attempt: int, base_s: float, cap_s: float,
                  retry_after_s: Optional[float] = None,
                  rng: Optional[random.Random] = None) -> float:
    """Delay before retry `attempt` (1-based): capped exponential with
    FULL jitter — `uniform(0, min(cap, base·2^(attempt-1)))` — so a
    retry storm from many clients decorrelates instead of re-arriving
    in lockstep. A server-provided Retry-After is a floor, not a
    suggestion: we never come back EARLIER than the daemon asked, and
    jitter is added on top (still capped) so even Retry-After herds
    spread out."""
    r = (rng or random).uniform(0.0, 1.0)
    exp = min(cap_s, base_s * (2.0 ** max(0, attempt - 1)))
    delay = r * exp
    if retry_after_s is not None:
        delay = min(retry_after_s + r * exp, retry_after_s + cap_s)
        delay = max(delay, retry_after_s)
    return delay


class ServiceClient:
    def __init__(self, base_url: str, timeout: float = 30.0,
                 max_attempts: int = 4, backoff_base_s: float = 0.1,
                 backoff_cap_s: float = 5.0,
                 rng: Optional[random.Random] = None):
        # base_url: http://host:port (path prefixes unsupported — the
        # daemon serves at the root, like core/serve.py).
        if "://" in base_url:
            base_url = base_url.split("://", 1)[1]
        self.netloc = base_url.rstrip("/")
        self.timeout = timeout
        self.max_attempts = max(1, int(max_attempts))
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self._rng = rng or random.Random()

    def _call_once(self, method: str, path: str,
                   body: Optional[dict] = None) -> dict:
        conn = HTTPConnection(self.netloc, timeout=self.timeout)
        try:
            payload = json.dumps(body).encode() if body is not None else None
            headers = {"Content-Type": "application/json"} if payload else {}
            conn.request(method, path, body=payload, headers=headers)
            resp = conn.getresponse()
            data = json.loads(resp.read() or b"{}")
        finally:
            conn.close()
        if resp.status >= 400:
            raise ServiceError(resp.status, data)
        return data

    def _call(self, method: str, path: str, body: Optional[dict] = None,
              retry: bool = True) -> dict:
        """One logical call with the retry discipline (module
        docstring). `retry=False` restores single-shot semantics for
        calls the caller wants to fail fast."""
        attempts = self.max_attempts if retry else 1
        last: Exception = None
        for attempt in range(1, attempts + 1):
            try:
                return self._call_once(method, path, body)
            except ServiceError as e:
                if e.status not in RETRYABLE_STATUSES or attempt == attempts:
                    raise
                last = e
                delay = backoff_delay(attempt, self.backoff_base_s,
                                      self.backoff_cap_s,
                                      retry_after_s=e.retry_after_s,
                                      rng=self._rng)
            except RETRYABLE_CONN_ERRORS as e:
                # Safe because /submit is idempotent (fingerprint
                # attach / cache hit) and every other endpoint is a
                # read or an idempotent cancel.
                if attempt == attempts:
                    raise
                last = e
                delay = backoff_delay(attempt, self.backoff_base_s,
                                      self.backoff_cap_s, rng=self._rng)
            time.sleep(delay)
        raise last  # unreachable; loop always returns or raises

    # ------------------------------------------------------- surface

    def submit(self, histories: Sequence, workload: str = "register",
               algorithm: str = "auto", deadline_ms: Optional[float] = None,
               priority: int = 0, retry: bool = True,
               consistency: str = "linearizable") -> dict:
        """Submit histories (History objects or op-dict lists); returns
        the daemon's request record ({"id", "status", ...}). Retries
        429/503/connection failures with capped jittered backoff up to
        `max_attempts` (safe: submission is idempotent); the final
        failure raises ServiceError (read `.retry_after_s`) or the
        connection error. `retry=False` fails fast. `consistency`
        selects the verdict's ladder rung (linearizable / sequential /
        session)."""
        rows = [h.to_dicts() if hasattr(h, "to_dicts") else list(h)
                for h in histories]
        return self._call("POST", "/submit", {
            "workload": workload, "histories": rows,
            "algorithm": algorithm, "deadline_ms": deadline_ms,
            "priority": priority, "consistency": consistency},
            retry=retry)

    def submit_run_dir(self, run_dir: str, workload: Optional[str] = None,
                       algorithm: str = "auto", retry: bool = True,
                       consistency: str = "linearizable") -> dict:
        return self._call("POST", "/submit", {
            "run_dir": str(run_dir), "workload": workload,
            "algorithm": algorithm, "consistency": consistency},
            retry=retry)

    def result(self, request_id: str,
               wait_s: Optional[float] = None) -> dict:
        path = f"/result?id={request_id}"
        if wait_s is not None:
            path += f"&wait_s={wait_s}"
        return self._call("GET", path)

    def cancel(self, request_id: str) -> dict:
        return self._call("POST", "/cancel", {"id": request_id})

    def stats(self) -> dict:
        return self._call("GET", "/stats")

    def healthz(self) -> dict:
        return self._call("GET", "/healthz")

    def check(self, histories: Sequence, workload: str = "register",
              algorithm: str = "auto", timeout_s: float = 300.0,
              poll_s: float = 0.05,
              consistency: str = "linearizable") -> dict:
        """Submit-and-wait convenience: returns the terminal request
        record (results included). Waits server-side in bounded slices
        so one slow verdict cannot park the connection past the
        daemon's handler cap."""
        rec = self.submit(histories, workload=workload, algorithm=algorithm,
                          consistency=consistency)
        if rec.get("status") in ("done", "failed", "cancelled"):
            return self.result(rec["id"])
        deadline = time.monotonic() + timeout_s
        while True:
            rec = self.result(rec["id"], wait_s=min(
                10.0, max(poll_s, deadline - time.monotonic())))
            if rec.get("status") in ("done", "failed", "cancelled"):
                return rec
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"request {rec['id']} still {rec.get('status')} after "
                    f"{timeout_s:.0f}s")

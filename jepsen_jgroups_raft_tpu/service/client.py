"""Thin HTTP client for graftd — stdlib http.client, JSON in/out.

The tenant-side counterpart of service/http.py: tests, the bench's
--service throughput mode, and any external submitter use this instead
of hand-rolling requests. One connection per call (the daemon is
ThreadingHTTPServer; connection reuse buys nothing at this scale and a
stateless client survives daemon restarts for free).

Retry discipline (ISSUE 8): submission is IDEMPOTENT server-side — a
resubmitted fingerprint attaches to the live request or hits the result
cache instead of double-checking — so the client can safely retry the
failure modes a durable daemon actually produces: 429 backpressure
(honoring the daemon's Retry-After), 503 while a restart is in flight
(same), and connection-level failures (daemon SIGKILL'd mid-call; the
request may or may not have been journaled — resubmitting is safe
either way, which is the whole point of idempotency). Backoff is capped
exponential with full jitter and a max-attempts cap; callers that want
the old single-shot behavior pass ``max_attempts=1``.

Cluster routing (ISSUE 11): pass ``replicas=[url…]`` and the client
routes across the fleet — affinity-first (rendezvous hash over the
submission payload, so identical resubmissions land on the replica
whose caches already hold the verdict), least-loaded fallback (a
replica that refused or failed is deprioritized until its advertised
retry-after elapses), and failover retry that is safe because
submission is idempotent and verdicts live in the shared store. Two
rules are deliberately CLUSTER-GLOBAL, not per replica: ``max_attempts``
caps the total tries across all replicas (N replicas must not multiply
the retry budget into a fleet-wide storm), and a Retry-After is a floor
across replicas (a shedding replica answers with the CLUSTER's best
hint, so hopping to the next replica before it elapses just burns an
attempt on the same full cluster). A dead replica's connection error,
by contrast, fails over to the next replica immediately — liveness
probing is not load backoff. ``/result`` fails over on 404 too: after a
journal handoff the request lives on the surviving replica that
adopted it.
"""

from __future__ import annotations

import hashlib
import json
import random
import time
from collections import OrderedDict
from http.client import HTTPConnection, HTTPException
from typing import List, Optional, Sequence

#: Connection-level failures safe to retry once submission is
#: idempotent (refused/reset/timeout — the daemon-restart signatures).
RETRYABLE_CONN_ERRORS = (ConnectionError, HTTPException, TimeoutError,
                         OSError)

#: HTTP statuses that carry a retry_after_s hint and mean "try later".
RETRYABLE_STATUSES = (429, 503)


class ServiceError(Exception):
    """Non-2xx daemon answer. `status` is the HTTP code; `payload` the
    decoded JSON body (carries `retry_after_s` on 429 AND 503)."""

    def __init__(self, status: int, payload: dict):
        super().__init__(f"HTTP {status}: {payload.get('error', payload)}")
        self.status = status
        self.payload = payload

    @property
    def retry_after_s(self) -> Optional[float]:
        v = self.payload.get("retry_after_s")
        return float(v) if v is not None else None


def backoff_delay(attempt: int, base_s: float, cap_s: float,
                  retry_after_s: Optional[float] = None,
                  rng: Optional[random.Random] = None) -> float:
    """Delay before retry `attempt` (1-based): capped exponential with
    FULL jitter — `uniform(0, min(cap, base·2^(attempt-1)))` — so a
    retry storm from many clients decorrelates instead of re-arriving
    in lockstep. A server-provided Retry-After is a floor, not a
    suggestion: we never come back EARLIER than the daemon asked, and
    jitter is added on top (still capped) so even Retry-After herds
    spread out."""
    r = (rng or random).uniform(0.0, 1.0)
    exp = min(cap_s, base_s * (2.0 ** max(0, attempt - 1)))
    delay = r * exp
    if retry_after_s is not None:
        delay = min(retry_after_s + r * exp, retry_after_s + cap_s)
        delay = max(delay, retry_after_s)
    return delay


def _netloc(url: str) -> str:
    if "://" in url:
        url = url.split("://", 1)[1]
    return url.rstrip("/")


class ServiceClient:
    def __init__(self, base_url: str, timeout: float = 30.0,
                 max_attempts: int = 4, backoff_base_s: float = 0.1,
                 backoff_cap_s: float = 5.0,
                 rng: Optional[random.Random] = None,
                 replicas: Optional[Sequence[str]] = None):
        # base_url: http://host:port (path prefixes unsupported — the
        # daemon serves at the root, like core/serve.py). `replicas`
        # (ISSUE 11) adds the rest of the cluster; base_url's replica
        # is included automatically and single-URL behavior is
        # byte-for-byte unchanged when it is omitted.
        self.netloc = _netloc(base_url)
        self.netlocs: List[str] = [self.netloc]
        for u in replicas or ():
            n = _netloc(u)
            if n not in self.netlocs:
                self.netlocs.append(n)
        self.timeout = timeout
        self.max_attempts = max(1, int(max_attempts))
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self._rng = rng or random.Random()
        #: wall time before which each replica is deprioritized in the
        #: fallback order (stamped from its Retry-After / failures) —
        #: the client-side half of least-loaded routing.
        self._penalty_until: dict = {}
        #: cluster-wide Retry-After floor (module docstring).
        self._floor_until = 0.0
        #: connection-level failovers performed (a replica died and the
        #: call moved on) — the bench's failover-latency evidence.
        self.failovers = 0
        #: request id → the replica that answered for it (bounded):
        #: result/cancel polls go straight to the owner instead of
        #: walking 404 probes across the fleet on every poll. A stale
        #: or lost hint only costs probes, never correctness.
        self._owner: "OrderedDict[str, str]" = OrderedDict()
        #: netloc that served the most recent successful _call (feeds
        #: the owner map; best-effort under concurrent use).
        self._answered_by: Optional[str] = None

    # ------------------------------------------------------- routing

    def _route(self, affinity: Optional[str] = None,
               prefer: Optional[str] = None) -> List[str]:
        """Replica order for one logical call: `prefer` (the known
        owner of the id being polled) first when given, else the affine
        replica (rendezvous hash — stable per payload, uniform across
        fingerprints), then the rest least-loaded-first (soonest
        penalty expiry; ties keep the configured order)."""
        if len(self.netlocs) == 1:
            return list(self.netlocs)
        if affinity:
            # ONE digest construction per route (ISSUE 15 satellite):
            # the affinity prefix is hashed once and each replica's
            # rendezvous key extends a cheap .copy() of that state —
            # byte-identical to sha256(f"{affinity}|{n}") (same input
            # stream), so the route order is unchanged, but the
            # per-replica rehash of the (payload-sized) key is gone.
            hd = hashlib.sha256(affinity.encode())

            def rendezvous(n: str) -> str:
                h = hd.copy()
                h.update(f"|{n}".encode())
                return h.hexdigest()

            ordered = sorted(self.netlocs, key=rendezvous, reverse=True)
        else:
            ordered = list(self.netlocs)
        now = time.monotonic()
        head, tail = ordered[:1], ordered[1:]
        tail.sort(key=lambda n: max(0.0,
                                    self._penalty_until.get(n, 0.0) - now))
        route = head + tail
        if prefer in self.netlocs and route[0] != prefer:
            route.remove(prefer)
            route.insert(0, prefer)
        return route

    def _remember_owner(self, request_id: Optional[str]) -> None:
        if not request_id or self._answered_by is None \
                or len(self.netlocs) == 1:
            return
        self._owner[request_id] = self._answered_by
        self._owner.move_to_end(request_id)
        while len(self._owner) > 1024:
            self._owner.popitem(last=False)

    def _penalize(self, netloc: str, for_s: float) -> None:
        self._penalty_until[netloc] = max(
            self._penalty_until.get(netloc, 0.0),
            time.monotonic() + max(0.1, for_s))

    def _call_once(self, method: str, path: str,
                   body: Optional[dict] = None,
                   netloc: Optional[str] = None) -> dict:
        conn = HTTPConnection(netloc or self.netloc, timeout=self.timeout)
        try:
            payload = json.dumps(body).encode() if body is not None else None
            headers = {"Content-Type": "application/json"} if payload else {}
            conn.request(method, path, body=payload, headers=headers)
            resp = conn.getresponse()
            data = json.loads(resp.read() or b"{}")
        finally:
            conn.close()
        if resp.status >= 400:
            raise ServiceError(resp.status, data)
        return data

    def _call(self, method: str, path: str, body: Optional[dict] = None,
              retry: bool = True, affinity: Optional[str] = None,
              failover_404: bool = False,
              prefer: Optional[str] = None) -> dict:
        """One logical call with the retry discipline (module
        docstring). `retry=False` restores single-shot semantics for
        calls the caller wants to fail fast. The attempt cap is
        CLUSTER-GLOBAL: every try, on whichever replica, counts against
        the same `max_attempts` budget — failover must not multiply
        the retry storm by the replica count (ISSUE 11 satellite)."""
        route = self._route(affinity, prefer=prefer)
        attempts = self.max_attempts if retry else 1
        last: Exception = None
        ri = 0
        seen_404 = 0
        attempt = 0
        while attempt < attempts:
            attempt += 1
            netloc = route[ri % len(route)]
            try:
                out = self._call_once(method, path, body, netloc=netloc)
                self._penalty_until.pop(netloc, None)
                self._answered_by = netloc
                return out
            except ServiceError as e:
                if e.status == 404 and failover_404 \
                        and seen_404 < len(route) - 1:
                    # the request may live on the replica that adopted
                    # a dead peer's journal: probe the rest of the
                    # fleet before concluding "unknown id". Probes are
                    # sequential reads, not retries — they do not
                    # consume the attempt budget.
                    attempt -= 1
                    seen_404 += 1
                    ri += 1
                    continue
                if e.status not in RETRYABLE_STATUSES \
                        or attempt >= attempts:
                    raise
                last = e
                if e.retry_after_s is not None:
                    # the daemon's hint is already the CLUSTER's best
                    # (its 429 consults peer leases): floor every
                    # replica behind it, not just the one that answered
                    self._floor_until = max(
                        self._floor_until,
                        time.monotonic() + e.retry_after_s)
                    self._penalize(netloc, e.retry_after_s)
                delay = backoff_delay(attempt, self.backoff_base_s,
                                      self.backoff_cap_s,
                                      retry_after_s=e.retry_after_s,
                                      rng=self._rng)
                delay = max(delay, self._floor_until - time.monotonic())
                ri += 1
            except RETRYABLE_CONN_ERRORS as e:
                # Safe because /submit is idempotent (fingerprint
                # attach / cache hit) and every other endpoint is a
                # read or an idempotent cancel.
                if attempt >= attempts:
                    raise
                last = e
                self._penalize(netloc, 1.0)
                ri += 1
                if len(route) > 1 and attempt < len(route):
                    # a dead replica is a liveness event, not load:
                    # fail over to the next replica immediately
                    self.failovers += 1
                    continue
                delay = backoff_delay(attempt, self.backoff_base_s,
                                      self.backoff_cap_s, rng=self._rng)
            time.sleep(max(0.0, delay))
        raise last  # unreachable; loop always returns or raises

    # ------------------------------------------------------- surface

    def submit(self, histories: Sequence, workload: str = "register",
               algorithm: str = "auto", deadline_ms: Optional[float] = None,
               priority: int = 0, retry: bool = True,
               consistency: str = "linearizable",
               affinity: bool = True) -> dict:
        """Submit histories (History objects or op-dict lists); returns
        the daemon's request record ({"id", "status", ...}). Retries
        429/503/connection failures with capped jittered backoff up to
        `max_attempts` (safe: submission is idempotent); the final
        failure raises ServiceError (read `.retry_after_s`) or the
        connection error. `retry=False` fails fast. `consistency`
        selects the verdict's ladder rung (linearizable / sequential /
        session)."""
        rows = [h.to_dicts() if hasattr(h, "to_dicts") else list(h)
                for h in histories]
        key = None
        if affinity and len(self.netlocs) > 1:
            # content-keyed affinity (ISSUE 11): identical payloads
            # route to the same replica, so idempotent resubmissions
            # attach/cache-hit there instead of fanning one fingerprint
            # across the fleet. Scheduling metadata (deadline,
            # priority) stays out of the key — it does not change the
            # verdict identity. `affinity=False` keeps the configured
            # replica order (the bench's failover phase pins the dead
            # replica at the head this way).
            key = hashlib.sha256(json.dumps(
                [workload, algorithm, consistency, rows],
                sort_keys=True, default=str).encode()).hexdigest()
        rec = self._call("POST", "/submit", {
            "workload": workload, "histories": rows,
            "algorithm": algorithm, "deadline_ms": deadline_ms,
            "priority": priority, "consistency": consistency},
            retry=retry, affinity=key)
        self._remember_owner(rec.get("id"))
        return rec

    def submit_run_dir(self, run_dir: str, workload: Optional[str] = None,
                       algorithm: str = "auto", retry: bool = True,
                       consistency: str = "linearizable") -> dict:
        return self._call("POST", "/submit", {
            "run_dir": str(run_dir), "workload": workload,
            "algorithm": algorithm, "consistency": consistency},
            retry=retry)

    def result(self, request_id: str,
               wait_s: Optional[float] = None) -> dict:
        path = f"/result?id={request_id}"
        if wait_s is not None:
            path += f"&wait_s={wait_s}"
        # The known owner (the replica that answered the submit or the
        # last poll) leads the route — polling must not walk 404
        # probes across the fleet on every call. 404 still fails over:
        # after a journal handoff the id answers from the survivor
        # that adopted it (ISSUE 11), and the owner map re-learns it.
        rec = self._call("GET", path,
                         failover_404=len(self.netlocs) > 1,
                         prefer=self._owner.get(request_id))
        self._remember_owner(request_id)
        return rec

    def cancel(self, request_id: str) -> dict:
        return self._call("POST", "/cancel", {"id": request_id},
                          prefer=self._owner.get(request_id))

    def stats(self) -> dict:
        return self._call("GET", "/stats")

    def healthz(self) -> dict:
        return self._call("GET", "/healthz")

    def stream(self, workload: str = "register", units: int = 1,
               algorithm: str = "auto",
               consistency: str = "linearizable",
               session_id: Optional[str] = None,
               resume: bool = False) -> "StreamSession":
        """Open (or resume) a streaming verdict session (ISSUE 12);
        returns a `StreamSession` whose `append`/`finish` carry the
        per-segment idempotent retry discipline."""
        s = StreamSession(self, workload=workload, units=units,
                          algorithm=algorithm, consistency=consistency,
                          session_id=session_id, resume=resume)
        s.open()
        return s

    def check(self, histories: Sequence, workload: str = "register",
              algorithm: str = "auto", timeout_s: float = 300.0,
              poll_s: float = 0.05,
              consistency: str = "linearizable") -> dict:
        """Submit-and-wait convenience: returns the terminal request
        record (results included). Waits server-side in bounded slices
        so one slow verdict cannot park the connection past the
        daemon's handler cap."""
        rec = self.submit(histories, workload=workload, algorithm=algorithm,
                          consistency=consistency)
        if rec.get("status") in ("done", "failed", "cancelled"):
            return self.result(rec["id"])
        deadline = time.monotonic() + timeout_s
        while True:
            rec = self.result(rec["id"], wait_s=min(
                10.0, max(poll_s, deadline - time.monotonic())))
            if rec.get("status") in ("done", "failed", "cancelled"):
                return rec
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"request {rec['id']} still {rec.get('status')} after "
                    f"{timeout_s:.0f}s")


class StreamSession:
    """Producer-side streaming session (ISSUE 12 tentpole (d)).

    Wraps one server-side stream session: `open` / `append` / `finish`
    with the per-segment idempotent retry discipline. The client owns
    the sequence numbers; a segment whose response was lost (connection
    error, daemon SIGKILL mid-call) is simply RE-SENT under the same
    seq — the server's duplicate detection (payload digest) makes the
    retry a no-op when the first copy landed, and the WAL makes it
    durable when it did not. 429/503/connection retries ride the
    owning ServiceClient's backoff (idempotent by construction, so the
    same safety argument as /submit applies).

    A crashed PRODUCER is recoverable too: a fresh process constructs
    the session with ``session_id=<sid>, resume=True`` — the server
    answers with its current state (including ``next_seq``), and the
    new producer continues from there (the kill-the-client scenario in
    scripts/chaos_graftd.py).
    """

    def __init__(self, client: ServiceClient, workload: str = "register",
                 units: int = 1, algorithm: str = "auto",
                 consistency: str = "linearizable",
                 session_id: Optional[str] = None,
                 resume: bool = False):
        self.client = client
        self.workload = workload
        self.units = units
        self.algorithm = algorithm
        self.consistency = consistency
        self.session_id = session_id
        self.resume = resume
        self.seq = 1
        self.last_state: Optional[dict] = None

    def open(self) -> dict:
        body = {"workload": self.workload, "units": self.units,
                "algorithm": self.algorithm,
                "consistency": self.consistency}
        if self.session_id:
            body["session"] = self.session_id
        if self.resume:
            body["resume"] = True
        rec = self.client._call("POST", "/stream/open", body)
        self.session_id = rec["session"]
        self.seq = int(rec.get("next_seq", 1))
        self.last_state = rec
        return rec

    @staticmethod
    def _rows(ops) -> list:
        if hasattr(ops, "to_dicts"):
            return ops.to_dicts()
        return [op.to_dict() if hasattr(op, "to_dict") else dict(op)
                for op in ops]

    def append(self, ops) -> dict:
        """Append one segment (a flat op list for single-unit sessions,
        or one list per unit). Assigns the next seq; safe to call again
        after any transport failure — the seq/digest pair makes the
        resend idempotent."""
        if ops and not isinstance(ops[0], (list, tuple)) \
                or hasattr(ops, "to_dicts"):
            payload = self._rows(ops)
        elif ops and isinstance(ops[0], (list, tuple)):
            payload = [self._rows(u) for u in ops]
        else:
            payload = list(ops)
        seq = self.seq
        # An honest retry of a landed-but-unanswered segment re-sends
        # the IDENTICAL payload and gets 200 {duplicate: true} from the
        # digest check — so any 409 here is a REAL conflict (a second
        # producer on the same session, or a client bug) and must
        # surface, never be silently resynced past: swallowing it would
        # drop a segment the server explicitly refused to merge.
        rec = self.client._call("POST", "/stream/append", {
            "session": self.session_id, "seq": seq, "ops": payload})
        self.seq = seq + 1
        self.last_state = rec
        return rec

    def status(self) -> dict:
        rec = self.client._call(
            "GET", f"/stream/status?session={self.session_id}")
        self.last_state = rec
        return rec

    def finish(self) -> dict:
        rec = self.client._call("POST", "/stream/finish",
                                {"session": self.session_id})
        self.last_state = rec
        return rec

    @property
    def violation(self) -> Optional[dict]:
        """The first mid-run violation the daemon has surfaced, if
        any (from the most recent response)."""
        return (self.last_state or {}).get("violation")

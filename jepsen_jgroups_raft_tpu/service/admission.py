"""Admission control: bounded queue with explicit backpressure + the
fingerprint result cache.

The queue is the service's ONLY elastic buffer, and it is deliberately
small (`JGRAFT_SERVICE_QUEUE`, default 64 requests): a checking daemon
that buffers unboundedly converts overload into an OOM of the host that
also owns the device mesh. Past capacity, admission fails loudly with a
`retry-after` estimate derived from observed service time — the client
retries, the daemon never falls over (the reject-with-retry-after
stance of every serving stack the batching scheduler borrows from;
PAPERS.md Orca/vLLM lineage).

The cache maps a submission fingerprint (content hash over the packed
event tensors — service/request.py) to its verdict list, LRU-bounded.
Identical resubmissions are a real production pattern for a checker
(CI re-runs, retry storms after a client timeout): they complete at
admission time without touching the queue or the mesh.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Callable, List, Optional

from ..platform import env_int
from .request import CheckRequest

#: Default queue capacity (requests, not rows).
DEFAULT_QUEUE_CAP = 64


def admit_frame(payload) -> CheckRequest:
    """Binary-lane admission (ISSUE 18): decode a submit frame's
    zero-copy tensor views and normalize them into a CheckRequest. The
    fingerprint is re-derived server-side over the received bytes
    (`request.admit_encoded`) — the lying-client argument lives there.
    Raises `frame.FrameError` (a ValueError → HTTP 400) on malformed
    frames, ValueError on unknown workloads/rungs exactly like the
    JSON path's `admit`."""
    from .frame import KIND_SUBMIT, FrameError, decode_frame
    from .request import admit_encoded

    fr = decode_frame(payload)
    if getattr(fr, "labels", None) is None:
        raise FrameError(f"expected a submit frame (kind {KIND_SUBMIT}); "
                         "got a stream segment")
    return admit_encoded(
        workload=fr.workload, labels=fr.labels, encs=fr.encs,
        algorithm=fr.algorithm, deadline_ms=fr.deadline_ms,
        priority=fr.priority, consistency=fr.consistency,
        claimed_fingerprint=fr.fingerprint)


def queue_capacity() -> int:
    """Resolved admission-queue bound (JGRAFT_SERVICE_QUEUE; parsed
    defensively like every other env gate — garbage warns and keeps
    the default, a zero/negative value clamps to 1)."""
    return env_int("JGRAFT_SERVICE_QUEUE", DEFAULT_QUEUE_CAP, minimum=1)


class QueueFull(Exception):
    """Admission rejected: the queue is at capacity. `retry_after_s` is
    the daemon's service-time-based estimate of when a slot frees."""

    def __init__(self, depth: int, retry_after_s: float):
        super().__init__(
            f"admission queue full ({depth} pending); "
            f"retry in ~{retry_after_s:.1f}s")
        self.depth = depth
        self.retry_after_s = retry_after_s


class ServiceStopped(RuntimeError):
    """Submission after shutdown: the daemon will never drain it. The
    queue itself enforces this (`close()` → `put` raises) so the check
    and the insert are one atomic step under the queue lock — a racing
    shutdown between a daemon-level flag check and the put cannot
    strand a request in a drained queue."""


class AdmissionQueue:
    """Bounded FIFO-arrival store of pending requests; ordering policy
    lives in the scheduler (it selects by effective deadline), this
    class owns capacity, wakeups, and cancelled-entry pruning."""

    def __init__(self, capacity: Optional[int] = None,
                 on_prune: Optional[Callable[[CheckRequest], None]] = None):
        self.capacity = capacity if capacity is not None else queue_capacity()
        self._pending: List[CheckRequest] = []  # guarded_by(_cond)
        self._cond = threading.Condition()
        self._closed = False  # guarded_by(_cond)
        #: called (outside the lock) for each cancelled entry pruned out.
        self._on_prune = on_prune

    @property
    def depth(self) -> int:
        with self._cond:
            return len(self._pending)

    def put(self, req: CheckRequest, retry_after_s: float) -> None:
        """Admit, or raise QueueFull (caller-computed estimate) /
        ServiceStopped (queue closed by shutdown — checked under the
        same lock as the insert, so no put can land after the drain)."""
        with self._cond:
            if self._closed:
                raise ServiceStopped("admission queue is closed")
            if len(self._pending) >= self.capacity:
                raise QueueFull(len(self._pending), retry_after_s)
            self._pending.append(req)
            self._cond.notify_all()

    def close(self) -> None:
        """Refuse all future puts (shutdown). The drain that follows is
        then complete: nothing can slip in after it."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def reopen(self) -> None:
        """Accept puts again (daemon restart via `start()`)."""
        with self._cond:
            self._closed = False

    def remove(self, req: CheckRequest) -> bool:
        """Pull a specific request back out (cancellation while queued).
        True when it was still pending — the caller owns finalizing it."""
        with self._cond:
            for i, r in enumerate(self._pending):
                if r is req:
                    del self._pending[i]
                    return True
        return False

    def take(self, chooser: Callable[[List[CheckRequest]],
                                     List[CheckRequest]],
             timeout: float) -> List[CheckRequest]:
        """Block up to `timeout` for `chooser` to select a non-empty
        batch from the pending snapshot; selected requests are removed
        atomically. Cancelled entries — and already-terminal ones (the
        stale twin of a watchdog requeue whose other copy finished
        first) — are pruned (and reported via on_prune) before every
        selection, so neither ever reaches execution."""
        deadline = time.monotonic() + timeout
        while True:
            pruned: List[CheckRequest] = []
            with self._cond:
                keep = []
                for r in self._pending:
                    (pruned if r.cancelled.is_set() or r.terminal
                     else keep).append(r)
                self._pending = keep
                chosen = chooser(list(self._pending)) if self._pending else []
                for r in chosen:
                    self._pending.remove(r)
                if not chosen:
                    remaining = deadline - time.monotonic()
                    if remaining > 0 and not pruned:
                        self._cond.wait(remaining)
            for r in pruned:
                if self._on_prune is not None:
                    self._on_prune(r)
            if chosen or time.monotonic() >= deadline:
                return chosen

    def requeue(self, reqs: List[CheckRequest]) -> None:
        """Put popped-but-unfinished requests back (worker-death
        recovery). Capacity is NOT re-enforced: these rows were already
        admitted once, and dropping them on a crash is the exact loss
        mode the supervisor exists to prevent."""
        with self._cond:
            # Identity-deduped: a watchdog strike can requeue a request
            # whose strike-one copy is still sitting in the queue.
            self._pending[:0] = [r for r in reqs
                                 if not r.cancelled.is_set()
                                 and not r.terminal
                                 and all(r is not p
                                         for p in self._pending)]
            self._cond.notify_all()


class ResultCache:
    """Thread-safe LRU of fingerprint → per-unit result list. Only
    clean (non-degraded) verdicts are stored: a degraded run's results
    carry a `platform-degraded` stamp that must describe THAT run, not
    replay into future submissions checked on a healthy platform."""

    def __init__(self, capacity: Optional[int] = None):
        self.capacity = (capacity if capacity is not None
                         else env_int("JGRAFT_SERVICE_CACHE", 256,
                                      minimum=0))
        self._lock = threading.Lock()
        self._entries: OrderedDict = OrderedDict()  # guarded_by(_lock)

    def get(self, fingerprint: str) -> Optional[List[dict]]:
        with self._lock:
            results = self._entries.get(fingerprint)
            if results is None:
                return None
            self._entries.move_to_end(fingerprint)
            return [dict(r) for r in results]

    def put(self, fingerprint: str, results: List[dict]) -> None:
        if self.capacity <= 0:
            return
        with self._lock:
            self._entries[fingerprint] = [dict(r) for r in results]
            self._entries.move_to_end(fingerprint)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

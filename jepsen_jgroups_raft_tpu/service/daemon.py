"""graftd: the always-on checking daemon.

Owns the pieces the rest of the package provides — admission queue +
result cache (service/admission.py), batching scheduler
(service/scheduler.py), request records (service/request.py) — and adds
the lifecycle: a supervised worker thread that drains the queue batch
by batch, service-level stats (throughput, queue depth high-water,
batch occupancy, latency percentiles, cache hits), and per-request
trace records written into the existing ``store/`` layout
(``store/<service>/<ts>-<reqid>/results.json``) so ``core/serve.py``
browses service verdicts exactly like test runs.

Failure stance:

* A batch whose DEVICE path dies degrades to the host ladder inside
  the scheduler — the request completes with ``platform-degraded``
  stamped, it does not error.
* A batch whose execution raises anyway (host fallback bug) fails only
  that batch's requests, with the error recorded; the worker loop
  continues.
* The worker THREAD dying (anything escaping the loop) loses nothing:
  the supervisor requeues the popped-but-unfinished batch, increments
  ``worker_restarts``, and respawns the worker. Queued requests were
  never popped, so they simply wait.
"""

from __future__ import annotations

import json
import logging
import statistics
import threading
import time
from collections import deque
from pathlib import Path
from typing import Optional, Sequence

from .admission import (AdmissionQueue, QueueFull, ResultCache,
                        ServiceStopped)
from .request import (CANCELLED, DONE, FAILED, QUEUED, RUNNING, CheckRequest,
                      admit, admit_run_dir)
from .scheduler import BatchScheduler, ShardLoads

LOG = logging.getLogger("jgraft.service")


class _ShardQueue:
    """Closeable per-shard work queue. The close/put race matters: a
    dispatcher routing a batch while shutdown drains the queues would
    otherwise strand the batch forever — its requests were already
    popped from the admission queue (so its drain misses them) and the
    executors have exited (the same shutdown/submit race PR 5 closed at
    the AdmissionQueue with `close()`; this is the routed-batch twin).
    `put` refuses under the same lock as the insert, so a routed batch
    either lands before `close_and_drain` (and is failed by it) or is
    refused (and the dispatcher fails it) — never silently stranded."""

    def __init__(self):
        self._cond = threading.Condition()
        self._items: deque = deque()
        self._closed = False

    def put(self, item) -> bool:
        with self._cond:
            if self._closed:
                return False
            self._items.append(item)
            self._cond.notify()
            return True

    def get(self, timeout: float):
        """Next item, or None on timeout / closed-and-empty."""
        with self._cond:
            if not self._items and not self._closed:
                self._cond.wait(timeout)
            if self._items:
                return self._items.popleft()
            return None

    def close_and_drain(self) -> list:
        with self._cond:
            self._closed = True
            items = list(self._items)
            self._items.clear()
            self._cond.notify_all()
            return items

    def reopen(self) -> None:
        with self._cond:
            self._closed = False


def default_workers() -> int:
    """Worker shards (JGRAFT_SERVICE_WORKERS, default 1 — today's
    single-worker daemon, bit for bit). On a multi-device or multi-host
    deployment set one worker per host/device group so independent
    shape-bucket batches check concurrently instead of serializing
    through one thread (ISSUE 7 tentpole (c)); defensively parsed."""
    from ..platform import env_int

    return env_int("JGRAFT_SERVICE_WORKERS", 1, minimum=1)

#: Poll granularity of the worker loop (also the shutdown latency
#: bound). The queue condition wakes the worker instantly on arrival;
#: this only bounds how often an idle worker re-checks the stop flag.
IDLE_POLL_S = 0.25

#: Latency samples kept for the percentile window.
LATENCY_WINDOW = 4096


def retain_capacity() -> int:
    """Terminal requests kept queryable after completion (the /result
    retention window, JGRAFT_SERVICE_RETAIN). Bounded for the same
    reason the queue is: an always-on daemon that retains every
    finished request's histories and encodings grows RSS without
    limit — the OOM the admission bound exists to prevent."""
    from ..platform import env_int

    return env_int("JGRAFT_SERVICE_RETAIN", 1024, minimum=1)


class CheckingService:
    """The daemon. `start()` spawns the supervised worker; `submit*`
    admit requests (raising `admission.QueueFull` past capacity);
    `shutdown()` drains in-flight work and joins every thread."""

    def __init__(self, store_root: Optional[str] = None,
                 name: str = "graftd",
                 queue_capacity: Optional[int] = None,
                 batch_wait: Optional[float] = None,
                 max_batch_rows: Optional[int] = None,
                 cache_capacity: Optional[int] = None,
                 check_fn=None, host_fallback=None,
                 n_workers: Optional[int] = None,
                 autostart: bool = True):
        self.name = name
        self.store_root = Path(store_root) if store_root else None
        self.queue = AdmissionQueue(queue_capacity,
                                    on_prune=self._finalize_pruned)
        self.cache = ResultCache(cache_capacity)
        self.scheduler = BatchScheduler(
            self.queue, check_fn=check_fn, host_fallback=host_fallback,
            max_batch_rows=max_batch_rows, batch_wait=batch_wait)
        # Worker shards (ISSUE 7): 1 = today's single supervised worker
        # executing inline; N > 1 = the same loop becomes a DISPATCHER
        # that routes each formed batch to the least-loaded shard's
        # executor thread, so independent shape buckets check
        # concurrently. Placement is stamped into per-request stats.
        self.n_workers = max(1, n_workers if n_workers is not None
                             else default_workers())
        self.shards = ShardLoads(self.n_workers)
        self._shard_queues: list = [_ShardQueue()
                                    for _ in range(self.n_workers)]
        self._executors: list = [None] * self.n_workers
        self._inflight_by_shard: dict = {}
        self._requests: dict = {}
        self._terminal: deque = deque()  # finished ids, oldest first
        self._retain = retain_capacity()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._started = False
        self._worker: Optional[threading.Thread] = None
        self._inflight: list = []
        self._latencies: deque = deque(maxlen=LATENCY_WINDOW)
        self._stats = {
            "submitted": 0, "completed": 0, "failed": 0, "cancelled": 0,
            "rejected": 0, "cache_hits": 0, "batches": 0, "batch_rows": 0,
            "batched_requests": 0, "degraded_batches": 0,
            "max_queue_depth": 0, "worker_restarts": 0, "trace_errors": 0,
        }
        self._service_time_s = 1.0  # EWMA of per-request service time
        if autostart:
            self.start()

    # ------------------------------------------------------- lifecycle

    def start(self) -> None:
        self._stop.clear()
        self.queue.reopen()
        for q in self._shard_queues:
            q.reopen()
        self._started = True
        self._ensure_worker()

    def _ensure_worker(self) -> None:
        """Spawn (or respawn after death) the supervised dispatcher and,
        for n_workers > 1, the per-shard executors. Called under submit
        too, so a STARTED daemon whose worker died serves the next
        tenant instead of silently queueing forever (a daemon built
        with autostart=False stays parked until `start()` — the
        deterministic-coalescing mode tests and the CI smoke use)."""
        with self._lock:
            if self._stop.is_set() or not self._started:
                return
            if self._worker is None or not self._worker.is_alive():
                self._worker = threading.Thread(
                    target=self._supervised_loop, daemon=True,
                    name=f"{self.name}-worker")
                self._worker.start()
            if self.n_workers > 1:
                for k in range(self.n_workers):
                    t = self._executors[k]
                    if t is None or not t.is_alive():
                        t = threading.Thread(
                            target=self._supervised_executor, args=(k,),
                            daemon=True, name=f"{self.name}-shard{k}")
                        self._executors[k] = t
                        t.start()

    def shutdown(self, wait: bool = True, timeout: float = 30.0) -> None:
        """Stop the workers; queued requests are failed loudly (a
        shutdown is not a verdict). Idempotent. The queue is CLOSED
        before the drain, so a submission racing this call either
        lands before the drain (and is failed by it) or gets
        ServiceStopped from `put` — never a silently-stranded entry."""
        self._stop.set()
        self.queue.close()
        # Close the shard queues BEFORE joining: a dispatcher mid-route
        # either landed its batch (drained here) or gets a refused put
        # and fails the batch itself — no window strands a routed batch
        # (see _ShardQueue). Executors wake on the close and exit.
        stranded = [item for q in self._shard_queues
                    for item in q.close_and_drain()]
        for batch, _rows, _placement in stranded:
            self._fail_unexecuted(batch)
        worker = self._worker
        if wait and worker is not None and worker.is_alive():
            worker.join(timeout)
        if wait:
            for t in self._executors:
                if t is not None and t.is_alive():
                    t.join(timeout)
        drained = self.queue.take(lambda pending: list(pending), timeout=0.0)
        for r in drained:
            r.finish(FAILED, error="service shut down before execution")
            self._count("failed")
            self._retire(r)

    # --------------------------------------------------------- worker

    def _supervised_loop(self) -> None:
        try:
            self._worker_loop()
        except BaseException:
            # The loop itself died (not a batch — _worker_loop contains
            # per-batch error handling). Requeue what was popped and
            # respawn: queued tenants must survive a worker bug.
            LOG.exception("%s worker died; restarting", self.name)
            with self._lock:
                inflight, self._inflight = self._inflight, []
            unfinished = [r for r in inflight
                          if r.status in (QUEUED, RUNNING)]
            for r in unfinished:
                r.status = QUEUED
            self.queue.requeue(unfinished)
            self._count("worker_restarts")
            if not self._stop.is_set():
                with self._lock:
                    self._worker = None
                self._ensure_worker()

    def _worker_loop(self) -> None:
        """Single-worker mode: form and execute inline (today's loop).
        Multi-worker mode (ISSUE 7): this loop is the DISPATCHER — it
        forms batches and routes each to the least-loaded shard's
        executor, so independent shape buckets run concurrently."""
        while not self._stop.is_set():
            batch = self.scheduler.next_batch(timeout=IDLE_POLL_S)
            if not batch:
                continue
            rows = sum(r.n_rows for r in batch)
            if self.n_workers == 1:
                placement = {"shard": 0, "n_shards": 1,
                             "loads_at_dispatch": self.shards.snapshot()}
                self.shards.add(0, rows)
                with self._lock:
                    self._inflight = list(batch)
                try:
                    self._run_batch(batch, placement)
                finally:
                    with self._lock:
                        self._inflight = []
                    self.shards.done(0, rows)
                continue
            k = self.shards.least_loaded()
            placement = {"shard": k, "n_shards": self.n_workers,
                         "loads_at_dispatch": self.shards.snapshot()}
            self.shards.add(k, rows)
            if not self._shard_queues[k].put((batch, rows, placement)):
                # Shutdown closed the shard queues between formation
                # and routing: fail the batch loudly, like the drains.
                self.shards.done(k, rows)
                self._fail_unexecuted(batch)

    def _fail_unexecuted(self, batch) -> None:
        """A shutdown is not a verdict: requests popped from admission
        but never executed fail with the same error the queue drains
        use."""
        for r in batch:
            if r.status in (QUEUED, RUNNING):
                r.finish(FAILED,
                         error="service shut down before execution")
                self._count("failed")
                self._retire(r)

    def _run_batch(self, batch, placement: dict) -> None:
        """Execute one formed batch (dispatcher inline or a shard
        executor): batch-level failures fail only this batch's
        requests; traces are written either way."""
        try:
            info = self.scheduler.execute(batch, placement=placement)
            self._account_batch(batch, info)
        except Exception:
            # Even the host fallback failed (or a scheduler bug):
            # fail THIS batch's requests, keep serving the queue.
            LOG.exception("%s batch execution failed", self.name)
            for r in batch:
                if r.status not in (DONE, CANCELLED, FAILED):
                    r.finish(FAILED, error="batch execution raised; "
                             "see service log")
            self._account_requests(batch)
        for r in batch:
            self._write_trace(r)

    def _supervised_executor(self, k: int) -> None:
        """Shard executor k: drain this shard's routed batches. The
        same survival contract as the dispatcher's supervisor: a dying
        executor requeues its popped-but-unfinished batch into the
        admission queue, bumps ``worker_restarts``, and is respawned —
        queued tenants must survive an executor bug."""
        try:
            q = self._shard_queues[k]
            while not self._stop.is_set():
                item = q.get(timeout=IDLE_POLL_S)
                if item is None:
                    continue
                batch, rows, placement = item
                with self._lock:
                    self._inflight_by_shard[k] = list(batch)
                try:
                    self._run_batch(batch, placement)
                finally:
                    with self._lock:
                        self._inflight_by_shard[k] = []
                    self.shards.done(k, rows)
        except BaseException:
            LOG.exception("%s shard %d executor died; restarting",
                          self.name, k)
            with self._lock:
                inflight = self._inflight_by_shard.pop(k, [])
            unfinished = [r for r in inflight
                          if r.status in (QUEUED, RUNNING)]
            for r in unfinished:
                r.status = QUEUED
            self.queue.requeue(unfinished)
            self._count("worker_restarts")
            if not self._stop.is_set():
                with self._lock:
                    self._executors[k] = None
                self._ensure_worker()

    # ------------------------------------------------------ admission

    def submit(self, histories: Sequence, workload: str = "register",
               algorithm: str = "auto", deadline_ms: Optional[float] = None,
               priority: int = 0) -> CheckRequest:
        """Admit a submission; returns its CheckRequest (already DONE on
        a cache hit). Raises QueueFull with a retry-after estimate when
        the queue is at capacity, ValueError on malformed input."""
        req = admit(histories, workload, algorithm=algorithm,
                    deadline_ms=deadline_ms, priority=priority)
        return self._admit(req)

    def submit_run_dir(self, run_dir, algorithm: str = "auto",
                       deadline_ms: Optional[float] = None,
                       priority: int = 0,
                       workload: Optional[str] = None) -> CheckRequest:
        """Admit a recorded-run directory (store/<name>/<ts>/)."""
        req = admit_run_dir(run_dir, algorithm=algorithm,
                            deadline_ms=deadline_ms, priority=priority,
                            workload=workload)
        return self._admit(req)

    def _admit(self, req: CheckRequest) -> CheckRequest:
        if self._stop.is_set():
            # Fast-path refusal; the authoritative (race-free) check is
            # the closed queue's own `put`, below.
            raise ServiceStopped(f"{self.name} is shut down")
        with self._lock:
            self._requests[req.id] = req
        cached = self.cache.get(req.fingerprint)
        if cached is not None and len(cached) == req.n_rows:
            req.cached = True
            req.finish(DONE, results=cached)
            self._count("submitted", "cache_hits", "completed")
            self._observe_latency(req)
            self._retire(req)
            self._write_trace(req)
            return req
        try:
            self.queue.put(req, retry_after_s=self._retry_after())
        except QueueFull:
            with self._lock:
                self._stats["rejected"] += 1
                del self._requests[req.id]
            raise
        except ServiceStopped:
            with self._lock:
                del self._requests[req.id]
            raise
        self._count("submitted")
        with self._lock:
            self._stats["max_queue_depth"] = max(
                self._stats["max_queue_depth"], self.queue.depth)
        self._ensure_worker()
        return req

    def _retry_after(self) -> float:
        """Backpressure hint: pending work over observed service rate.
        Never below half a second — a zero would invite a hot retry
        loop from the very client the bound exists to absorb."""
        with self._lock:
            est = self._service_time_s
        return round(max(0.5, self.queue.depth * est), 2)

    # -------------------------------------------------------- queries

    def get(self, request_id: str) -> Optional[CheckRequest]:
        with self._lock:
            return self._requests.get(request_id)

    def cancel(self, request_id: str) -> Optional[str]:
        """Cancel a request: pulled straight out if still queued,
        honored at demux if already riding a launch. Returns the
        request's status, or None for an unknown id."""
        req = self.get(request_id)
        if req is None:
            return None
        req.cancelled.set()
        if self.queue.remove(req):
            req.finish(CANCELLED)
            self._count("cancelled")
            self._retire(req)
            self._write_trace(req)
        return req.status

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._stats)
            lat = list(self._latencies)
        out["queue_depth"] = self.queue.depth
        out["cache_entries"] = len(self.cache)
        out["queue_capacity"] = self.queue.capacity
        out["batch_occupancy_mean"] = round(
            out["batched_requests"] / out["batches"], 3) \
            if out["batches"] else 0.0
        if lat:
            lat.sort()
            out["p50_latency_s"] = round(statistics.median(lat), 4)
            out["p99_latency_s"] = round(
                lat[min(len(lat) - 1, int(0.99 * len(lat)))], 4)
        worker = self._worker
        out["worker_alive"] = bool(worker is not None and worker.is_alive())
        out["workers"] = self.n_workers
        out["shard_loads"] = self.shards.snapshot()
        return out

    # ----------------------------------------------------- accounting

    def _count(self, *keys: str) -> None:
        with self._lock:
            for k in keys:
                if k in self._stats:
                    self._stats[k] += 1

    def _retire(self, req: CheckRequest) -> None:
        """Enter a terminal request into the bounded retention window;
        the oldest finished requests (and their histories/encodings)
        are dropped from the registry past JGRAFT_SERVICE_RETAIN —
        in-flight requests are never evicted (only terminal ids enter
        the window)."""
        if getattr(req, "_retired", False):
            return
        req._retired = True
        with self._lock:
            self._terminal.append(req.id)
            while len(self._terminal) > self._retain:
                self._requests.pop(self._terminal.popleft(), None)

    def _observe_latency(self, req: CheckRequest) -> None:
        dt = time.monotonic() - req.submitted
        with self._lock:
            self._latencies.append(dt)
            # EWMA feeds the retry-after estimate; per-REQUEST time.
            self._service_time_s = 0.8 * self._service_time_s + 0.2 * dt

    def _account_batch(self, batch, info: dict) -> None:
        with self._lock:
            self._stats["batches"] += 1
            self._stats["batch_rows"] += info["rows"]
            self._stats["batched_requests"] += info["requests"]
            if info["degraded"]:
                self._stats["degraded_batches"] += 1
        self._account_requests(batch)

    def _account_requests(self, batch) -> None:
        for r in batch:
            if r.status == DONE:
                self._count("completed")
                self._observe_latency(r)
                if not r.stats.get("degraded") and not any(
                        "platform-degraded" in res for res in r.results):
                    # Cache only verdicts free of ANY degrade stamp —
                    # including the process-registry stamp check_encoded
                    # applies (a silently-pinned-CPU process), not just
                    # this scheduler's local degrade path. A cached
                    # stamp would replay onto a healed platform.
                    self.cache.put(r.fingerprint, r.results)
            elif r.status == CANCELLED:
                self._count("cancelled")
            elif r.status == FAILED:
                self._count("failed")
            if r.status in (DONE, CANCELLED, FAILED):
                self._retire(r)

    def _finalize_pruned(self, req: CheckRequest) -> None:
        """Queue pruned a cancelled entry before it reached a batch."""
        if req.status not in (DONE, CANCELLED, FAILED):
            req.finish(CANCELLED)
            self._count("cancelled")
            self._retire(req)
            self._write_trace(req)

    # ---------------------------------------------------------- trace

    def _write_trace(self, req: CheckRequest) -> None:
        """Persist one request's terminal record into the store layout
        (store/<service>/<ts>-<reqid>/: results.json + history.jsonl),
        browsable by `core/serve.py` next to test runs. Best-effort:
        trace IO must never fail a verdict (counted, logged)."""
        if self.store_root is None or req.status == QUEUED:
            return
        try:
            from ..core.store import _jsonable

            ts = time.strftime("%Y%m%dT%H%M%S", time.localtime())
            d = self.store_root / self.name / f"{ts}-{req.id}"
            d.mkdir(parents=True, exist_ok=True)
            payload = _jsonable(req.to_dict())
            with open(d / "results.json", "w") as f:
                json.dump(payload, f, indent=2)
            with open(d / "history.jsonl", "w") as f:
                for label, hist in req.units:
                    for op in hist:
                        row = dict(op.to_dict(), unit=label)
                        f.write(json.dumps(_jsonable(row)) + "\n")
        except OSError:
            self._count("trace_errors")
            LOG.warning("trace write failed for request %s", req.id,
                        exc_info=True)

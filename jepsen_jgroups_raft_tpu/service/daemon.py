"""graftd: the always-on checking daemon.

Owns the pieces the rest of the package provides — admission queue +
result cache (service/admission.py), batching scheduler
(service/scheduler.py), request records (service/request.py) — and adds
the lifecycle: a supervised worker thread that drains the queue batch
by batch, service-level stats (throughput, queue depth high-water,
batch occupancy, latency percentiles, cache hits), and per-request
trace records written into the existing ``store/`` layout
(``store/<service>/<ts>-<reqid>/results.json``) so ``core/serve.py``
browses service verdicts exactly like test runs.

Failure stance:

* A batch whose DEVICE path dies degrades to the host ladder inside
  the scheduler — the request completes with ``platform-degraded``
  stamped, it does not error.
* A batch whose execution raises anyway (host fallback bug) fails only
  that batch's requests, with the error recorded; the worker loop
  continues.
* The worker THREAD dying (anything escaping the loop) loses nothing:
  the supervisor requeues the popped-but-unfinished batch, increments
  ``worker_restarts``, and respawns the worker. Queued requests were
  never popped, so they simply wait.
"""

from __future__ import annotations

import json
import logging
import os
import statistics
import threading
import time
from collections import deque
from pathlib import Path
from typing import Optional, Sequence

from .admission import (AdmissionQueue, QueueFull, ResultCache,
                        ServiceStopped)
from .journal import AdmissionJournal, decode_request, journal_enabled
from .request import (CANCELLED, DONE, FAILED, QUEUED, RUNNING, CheckRequest,
                      admit, admit_run_dir)
from .scheduler import BatchScheduler, ShardLoads
from .stream import StreamManager

LOG = logging.getLogger("jgraft.service")


class _ShardQueue:
    """Closeable per-shard work queue. The close/put race matters: a
    dispatcher routing a batch while shutdown drains the queues would
    otherwise strand the batch forever — its requests were already
    popped from the admission queue (so its drain misses them) and the
    executors have exited (the same shutdown/submit race PR 5 closed at
    the AdmissionQueue with `close()`; this is the routed-batch twin).
    `put` refuses under the same lock as the insert, so a routed batch
    either lands before `close_and_drain` (and is failed by it) or is
    refused (and the dispatcher fails it) — never silently stranded."""

    def __init__(self):
        self._cond = threading.Condition()
        self._items: deque = deque()  # guarded_by(_cond)
        self._closed = False  # guarded_by(_cond)

    def put(self, item) -> bool:
        with self._cond:
            if self._closed:
                return False
            self._items.append(item)
            self._cond.notify()
            return True

    def get(self, timeout: float):
        """Next item, or None on timeout / closed-and-empty."""
        with self._cond:
            if not self._items and not self._closed:
                self._cond.wait(timeout)
            if self._items:
                return self._items.popleft()
            return None

    def close_and_drain(self) -> list:
        with self._cond:
            self._closed = True
            items = list(self._items)
            self._items.clear()
            self._cond.notify_all()
            return items

    def reopen(self) -> None:
        with self._cond:
            self._closed = False


def default_workers() -> int:
    """Worker shards (JGRAFT_SERVICE_WORKERS, default 1 — today's
    single-worker daemon, bit for bit). On a multi-device or multi-host
    deployment set one worker per host/device group so independent
    shape-bucket batches check concurrently instead of serializing
    through one thread (ISSUE 7 tentpole (c)); defensively parsed."""
    from ..platform import env_int

    return env_int("JGRAFT_SERVICE_WORKERS", 1, minimum=1)

#: Poll granularity of the worker loop (also the shutdown latency
#: bound). The queue condition wakes the worker instantly on arrival;
#: this only bounds how often an idle worker re-checks the stop flag.
IDLE_POLL_S = 0.25

#: Latency samples kept for the percentile window.
LATENCY_WINDOW = 4096


def retain_capacity() -> int:
    """Terminal requests kept queryable after completion (the /result
    retention window, JGRAFT_SERVICE_RETAIN). Bounded for the same
    reason the queue is: an always-on daemon that retains every
    finished request's histories and encodings grows RSS without
    limit — the OOM the admission bound exists to prevent."""
    from ..platform import env_int

    return env_int("JGRAFT_SERVICE_RETAIN", 1024, minimum=1)


def default_crash_cap() -> int:
    """Executor deaths tolerated per request before quarantine
    (JGRAFT_SERVICE_CRASH_CAP, default 2 — one batched attempt, one
    solo attempt after the split). The unbounded alternative is the
    ISSUE-8 failure mode: a deterministically-crashing batch re-kills
    the supervised worker forever."""
    from ..platform import env_int

    return env_int("JGRAFT_SERVICE_CRASH_CAP", 2, minimum=1)


def default_watchdog_margin() -> float:
    """Hung-batch watchdog margin in seconds past a request's DEADLINE
    (JGRAFT_SERVICE_WATCHDOG_S, default 30; 0 disables). Strike one at
    deadline+margin requeues the request; strike two at
    deadline+2·margin retries it solo via the bounded host ladder
    (`check_encoded_host`) so a wedged device launch can never park a
    shard queue forever. Parsed as a float: sub-second margins are how
    the watchdog tests keep their wall clock down, and the old
    `float(env_int(...))` form silently discarded `0.5` to the
    default."""
    from ..platform import env_float

    return env_float("JGRAFT_SERVICE_WATCHDOG_S", 30.0, minimum=0.0)


class CheckingService:
    """The daemon. `start()` spawns the supervised worker; `submit*`
    admit requests (raising `admission.QueueFull` past capacity);
    `shutdown()` drains in-flight work and joins every thread."""

    def __init__(self, store_root: Optional[str] = None,
                 name: str = "graftd",
                 queue_capacity: Optional[int] = None,
                 batch_wait: Optional[float] = None,
                 max_batch_rows: Optional[int] = None,
                 cache_capacity: Optional[int] = None,
                 check_fn=None, host_fallback=None,
                 n_workers: Optional[int] = None,
                 journal_dir: Optional[str] = None,
                 crash_cap: Optional[int] = None,
                 watchdog_margin_s: Optional[float] = None,
                 cluster_dir: Optional[str] = None,
                 replica_id: Optional[str] = None,
                 advertise_url: Optional[str] = None,
                 lease_ttl_s: Optional[float] = None,
                 autostart: bool = True):
        self.name = name
        self.store_root = Path(store_root) if store_root else None
        self.queue = AdmissionQueue(queue_capacity,
                                    on_prune=self._finalize_pruned)
        self.cache = ResultCache(cache_capacity)
        self.scheduler = BatchScheduler(
            self.queue, check_fn=check_fn, host_fallback=host_fallback,
            max_batch_rows=max_batch_rows, batch_wait=batch_wait)
        # Worker shards (ISSUE 7): 1 = today's single supervised worker
        # executing inline; N > 1 = the same loop becomes a DISPATCHER
        # that routes each formed batch to the least-loaded shard's
        # executor thread, so independent shape buckets check
        # concurrently. Placement is stamped into per-request stats.
        self.n_workers = max(1, n_workers if n_workers is not None
                             else default_workers())
        self.shards = ShardLoads(self.n_workers)
        self._shard_queues: list = [_ShardQueue()
                                    for _ in range(self.n_workers)]
        self._executors: list = [None] * self.n_workers
        #: thread id → the batch that thread popped and is executing.
        #: Keyed by THREAD (not shard): after a watchdog replacement
        #: the zombie and its successor coexist briefly, and the
        #: zombie's cleanup must not clobber the successor's record.
        self._inflight_by_thread: dict = {}  # guarded_by(_lock)
        self._requests: dict = {}  # guarded_by(_lock)
        # finished ids, oldest first
        self._terminal: deque = deque()  # guarded_by(_lock)
        self._retain = retain_capacity()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._started = False
        self._worker: Optional[threading.Thread] = None
        self._latencies: deque = deque(maxlen=LATENCY_WINDOW)  # guarded_by(_lock)
        # Durability/resilience tier (ISSUE 8).
        self.crash_cap = (crash_cap if crash_cap is not None
                          else default_crash_cap())
        self.watchdog_margin_s = (
            watchdog_margin_s if watchdog_margin_s is not None
            else default_watchdog_margin())
        self._watchdog: Optional[threading.Thread] = None
        #: fingerprint → live (queued/running) primary request, and
        #: primary id → attached idempotent-duplicate followers.
        self._primary_by_fp: dict = {}  # guarded_by(_lock)
        self._followers: dict = {}  # guarded_by(_lock)
        self._stats = {  # guarded_by(_lock)
            "submitted": 0, "completed": 0, "failed": 0, "cancelled": 0,
            "rejected": 0, "cache_hits": 0, "batches": 0, "batch_rows": 0,
            "batched_requests": 0, "degraded_batches": 0,
            "max_queue_depth": 0, "worker_restarts": 0, "trace_errors": 0,
            "recovered_requests": 0, "attached_requests": 0,
            "quarantined": 0, "watchdog_requeues": 0,
            # lin-rung fast lane (ISSUE 14): requests fully decided by
            # the host certifier at dispatch — never a batch slot, a
            # shard queue, or a kernel launch. Always in the schema,
            # zero when the lane is off.
            "fastpath_requests": 0,
            # cluster tier (ISSUE 11) — always in the schema, zero when
            # clustering is not configured (the seam stays inert)
            "store_hits": 0, "store_puts": 0,
            "handoff_claims": 0, "handoff_requests": 0,
        }
        #: ISSUE 13: daemon-wide decided-tier counters ({tier: rows}
        #: over every demuxed verdict) — the fleet capacity-model
        #: metric, merged per batch and served by /stats. Kept outside
        #: _stats so _count's int arithmetic never sees a dict.
        self._tier_counts: dict = {}  # guarded_by(_lock)
        self._service_time_s = 1.0  # EWMA of per-request service time
        # Cluster tier (ISSUE 11): constructed only when a cluster dir
        # is configured — the single-replica daemon never imports the
        # module. Created BEFORE the journal (the shared layout owns
        # the WAL path) and before _recover (the manager's first lease
        # re-arms liveness before the boot-time replay window, so a
        # restarting replica's peers do not claim the WAL it is
        # replaying).
        self.cluster = None
        from ..platform import env_str

        cdir = (cluster_dir if cluster_dir is not None else
                env_str("JGRAFT_SERVICE_CLUSTER_DIR") or None)
        if cdir:
            from .cluster import ClusterManager

            rid = (replica_id
                   or env_str("JGRAFT_SERVICE_REPLICA_ID")
                   or f"{self.name}-{os.getpid()}")
            url = (advertise_url
                   or env_str("JGRAFT_SERVICE_ADVERTISE_URL") or None)
            self.cluster = ClusterManager(self, cdir, rid, url=url,
                                          lease_ttl=lease_ttl_s,
                                          autostart=autostart)
        self._journal: Optional[AdmissionJournal] = None
        if journal_enabled() and (journal_dir or self.cluster is not None
                                  or self.store_root):
            root = (Path(journal_dir) if journal_dir
                    else self.cluster.journal_dir()
                    if self.cluster is not None
                    else self.store_root / self.name / "journal")
            if self.cluster is not None and not journal_dir \
                    and self.store_root is not None:
                self._migrate_legacy_journal(root)
            self._journal = AdmissionJournal(root, retain=self._retain)
        # Streaming session tier (ISSUE 12): always constructed — the
        # in-memory mode works without a journal; crash resume and
        # idle-park resumability need one (stream.py docstring).
        self.streams = StreamManager(self)
        if self._journal is not None:
            self._recover()
        if autostart:
            self.start()

    # ------------------------------------------------------- recovery

    def _migrate_legacy_journal(self, root: Path) -> None:
        """First boot after clustering is enabled on a daemon that was
        running durable single-replica: the PR 8 per-daemon WAL
        (store/<name>/journal/wal.jsonl) is moved into the shared
        layout so its accepted-but-unfinished entries replay instead of
        being silently abandoned at the legacy path. When BOTH WALs
        exist (a partial earlier migration or manual copy) the cluster
        one wins and the legacy one is reported loudly — guessing at a
        record-level merge could double-admit."""
        import shutil

        legacy = self.store_root / self.name / "journal" / "wal.jsonl"
        target = root / "wal.jsonl"
        if not legacy.exists():
            return
        if target.exists():
            LOG.warning("%s: legacy journal %s left in place (a WAL "
                        "already exists at %s); entries there will NOT "
                        "replay — inspect and remove it manually",
                        self.name, legacy, target)
            return
        try:
            root.mkdir(parents=True, exist_ok=True)
            # shutil.move survives a cross-filesystem store/cluster
            # split, where os.replace would EXDEV; startup-only (runs
            # before worker threads or peers can race the WAL path)
            shutil.move(str(legacy), str(target))  # lint: allow(nonatomic-publish)
            LOG.warning("%s: migrated legacy journal %s into the "
                        "cluster layout at %s", self.name, legacy,
                        target)
        except OSError:
            LOG.warning("%s: legacy journal migration failed; entries "
                        "at %s will not replay", self.name, legacy,
                        exc_info=True)

    def _recover(self) -> None:
        """Crash recovery (ISSUE 8): replay the admission journal.
        Finished entries are restored into the retention window (their
        clean results also re-warm the fingerprint cache); unfinished
        entries re-enter the admission queue in original deadline
        order, except that a replayed duplicate whose fingerprint now
        cache-hits (or matches an earlier replayed primary) short-
        circuits instead of re-executing."""
        try:
            replayed = self._journal.replay()
        except OSError:
            LOG.warning("%s journal replay failed; starting with an "
                        "empty queue", self.name, exc_info=True)
            return
        for sub, term in replayed["finished"]:
            try:
                req = decode_request(sub)
            except (ValueError, KeyError, TypeError):
                continue
            req._journaled = True   # already has its terminal marker
            req._retired = True     # do not re-journal / re-resolve
            req.replayed = True
            status = term.get("status", FAILED)
            results = term.get("results")
            if status == DONE and results is None:
                # The verdict existed but was not persisted (degraded
                # runs never are): restored as FAILED so the client
                # resubmits for a fresh one instead of reading a DONE
                # with no results.
                status, error = FAILED, ("verdict was not persisted "
                                         "across restart; resubmit")
            else:
                error = term.get("error")
            req.finish(status, results=results, error=error)
            with self._lock:
                self._requests[req.id] = req
                self._terminal.append(req.id)
            if status == DONE and results is not None \
                    and len(results) == req.n_rows:
                # WAL terminals never persist degraded results (the
                # encode_terminal gate strips them, and the DONE-with-
                # no-results arm above re-fails such rows), so a
                # journal-replayed verdict is clean by construction
                self.cache.put(req.fingerprint, results)  # lint: allow(degraded)
                # lift the WAL terminal record into the shared store
                # (ISSUE 11): a verdict this replica computed before
                # the restart becomes a fleet-wide cache hit
                if self.cluster is not None and \
                        self.cluster.store.put(req.fingerprint, results):
                    self._count("store_puts")
        recovered = []
        for req in replayed["unfinished"]:
            req._journaled = True
            with self._lock:
                self._requests[req.id] = req
            cached = self.cache.get(req.fingerprint)
            if cached is None and self.cluster is not None:
                # another replica may have verified this fingerprint
                # while we were down — a cold-started replica warms
                # from the store instead of re-checking (ISSUE 11)
                stored = self.cluster.store.get(req.fingerprint)
                if stored is not None and len(stored) == req.n_rows:
                    cached = stored
                    self.cache.put(req.fingerprint, stored)
                    self._count("store_hits")
            if cached is not None and len(cached) == req.n_rows:
                req.cached = True
                req.finish(DONE, results=cached)
                self._count("cache_hits", "completed")
                self._retire(req)
                continue
            with self._lock:
                primary = self._primary_by_fp.get(req.fingerprint)
                if primary is not None and not primary.terminal:
                    # replayed duplicate: attach, don't re-execute
                    req.attached_to = primary.id
                    self._followers.setdefault(primary.id,
                                               []).append(req)
                    self._stats["attached_requests"] += 1
                    self._stats["recovered_requests"] += 1
                    continue
                self._primary_by_fp[req.fingerprint] = req
            recovered.append(req)
        if recovered:
            # requeue(): replayed entries were admitted once already —
            # capacity is not re-enforced against them (the same stance
            # as worker-death recovery). replay() sorted by deadline.
            self.queue.requeue(recovered)
            with self._lock:
                self._stats["recovered_requests"] += len(recovered)
        with self._lock:
            while len(self._terminal) > self._retain:
                self._requests.pop(self._terminal.popleft(), None)
        # Stream sessions (ISSUE 12): finished ones restore as terminal
        # stubs, unfinished ones as parked RESUMABLE stubs — the first
        # post-restart touch replays their journaled segments through
        # the identical pipeline (boot stays fast; rebuild is lazy).
        streams = replayed.get("streams") or {}
        if streams:
            self.streams.restore(streams)
        if recovered or replayed["finished"] or replayed["skipped"] \
                or streams:
            LOG.info("%s journal replay: %d unfinished requeued, %d "
                     "finished restored, %d stream session(s) restored, "
                     "%d corrupt/truncated record(s) skipped", self.name,
                     len(recovered), len(replayed["finished"]),
                     len(streams), replayed["skipped"])

    def adopt_requests(self, reqs, origin: str = "") -> int:
        """Re-own an expired replica's unfinished journal entries
        (ISSUE 11 tentpole (c); called by ClusterManager._adopt after
        its atomic rename claim). Each adopted request is re-journaled
        into THIS replica's WAL before it becomes runnable — the
        durability chain has no gap: until the claimed dir is removed
        the entry exists there, and from the append here it exists in
        our WAL under our live lease. Dedup mirrors _recover: a
        fingerprint the caches or a live primary already cover
        short-circuits instead of re-executing (resubmit-at-most-once,
        cluster-wide)."""
        taken = 0
        recovered = []
        for req in reqs:
            if self._stop.is_set():
                # shutdown mid-adoption: entries not taken stay in the
                # claimed dir (the manager skips its cleanup when we
                # report a partial take), so nothing is orphaned
                break
            req.replayed = True
            if self._journal is not None:
                req._journaled = True
            with self._lock:
                self._requests[req.id] = req
            if self._journal is not None:
                self._journal.append_submit(req)
            self._count("handoff_requests")
            taken += 1
            cached = self.cache.get(req.fingerprint)
            if cached is None and self.cluster is not None:
                stored = self.cluster.store.get(req.fingerprint)
                if stored is not None and len(stored) == req.n_rows:
                    cached = stored
                    self.cache.put(req.fingerprint, stored)
                    self._count("store_hits")
            if cached is not None and len(cached) == req.n_rows:
                req.cached = True
                req.finish(DONE, results=cached)
                self._count("completed")
                self._retire(req)
                self._write_trace(req)
                continue
            attached = False
            with self._lock:
                primary = self._primary_by_fp.get(req.fingerprint)
                if primary is not None and not primary.terminal:
                    req.attached_to = primary.id
                    self._followers.setdefault(primary.id, []).append(req)
                    self._stats["attached_requests"] += 1
                    attached = True
                else:
                    self._primary_by_fp[req.fingerprint] = req
            if not attached:
                recovered.append(req)
        if recovered:
            # replay() delivered them deadline-sorted; requeue preserves
            # that order at the head (adopted work was admitted before
            # anything currently queued here)
            self.queue.requeue(recovered)
        if taken:
            self._ensure_worker()
            LOG.warning("%s adopted %d unfinished request(s) from "
                        "expired replica %s (%d requeued)", self.name,
                        taken, origin or "<unknown>", len(recovered))
        return taken

    # ------------------------------------------------------- lifecycle

    def start(self) -> None:
        self._stop.clear()
        self.queue.reopen()
        for q in self._shard_queues:
            q.reopen()
        self._started = True
        if self.cluster is not None:
            self.cluster.start()
        self._ensure_worker()

    def _ensure_worker(self) -> None:
        """Spawn (or respawn after death) the supervised dispatcher and,
        for n_workers > 1, the per-shard executors. Called under submit
        too, so a STARTED daemon whose worker died serves the next
        tenant instead of silently queueing forever (a daemon built
        with autostart=False stays parked until `start()` — the
        deterministic-coalescing mode tests and the CI smoke use)."""
        with self._lock:
            if self._stop.is_set() or not self._started:
                return
            if self._worker is None or not self._worker.is_alive():
                self._worker = threading.Thread(
                    target=self._supervised_loop, daemon=True,
                    name=f"{self.name}-worker")
                self._worker.start()
            if self.n_workers > 1:
                for k in range(self.n_workers):
                    t = self._executors[k]
                    if t is None or not t.is_alive():
                        t = threading.Thread(
                            target=self._supervised_executor, args=(k,),
                            daemon=True, name=f"{self.name}-shard{k}")
                        self._executors[k] = t
                        t.start()
            if self.watchdog_margin_s > 0 and (
                    self._watchdog is None
                    or not self._watchdog.is_alive()):
                self._watchdog = threading.Thread(
                    target=self._watchdog_loop, daemon=True,
                    name=f"{self.name}-watchdog")
                self._watchdog.start()

    def shutdown(self, wait: bool = True, timeout: float = 30.0) -> None:
        """Stop the workers; queued requests are failed loudly (a
        shutdown is not a verdict). Idempotent. The queue is CLOSED
        before the drain, so a submission racing this call either
        lands before the drain (and is failed by it) or gets
        ServiceStopped from `put` — never a silently-stranded entry."""
        self._stop.set()
        # Stop the cluster agent FIRST (joins its thread): a handoff
        # adoption racing this shutdown would otherwise requeue adopted
        # entries after the drain below and strand them. Entries it
        # already re-journaled are safe either way — they are in OUR
        # WAL, so the drain's terminal markers (or a later replay)
        # account for them.
        if self.cluster is not None:
            self.cluster.shutdown()
        self.queue.close()
        # Close the shard queues BEFORE joining: a dispatcher mid-route
        # either landed its batch (drained here) or gets a refused put
        # and fails the batch itself — no window strands a routed batch
        # (see _ShardQueue). Executors wake on the close and exit.
        stranded = [item for q in self._shard_queues
                    for item in q.close_and_drain()]
        for batch, _rows, _placement in stranded:
            self._fail_unexecuted(batch)
        worker = self._worker
        if wait and worker is not None and worker.is_alive():
            worker.join(timeout)
        if wait:
            for t in self._executors:
                if t is not None and t.is_alive():
                    t.join(timeout)
            wd = self._watchdog
            if wd is not None and wd.is_alive():
                wd.join(timeout)
        drained = self.queue.take(lambda pending: list(pending), timeout=0.0)
        for r in drained:
            if r.finish(FAILED, error="service shut down before execution"):
                self._count("failed")
            self._retire(r)
        # Stream sessions survive shutdown BY DESIGN (unlike queued
        # batch requests, which are failed loudly above): their
        # journaled segments make them resumable — a clean restart is
        # indistinguishable from a crash to a streaming producer.
        self.streams.shutdown()
        if self._journal is not None:
            self._journal.close()

    # --------------------------------------------------------- worker

    def _supervised_loop(self) -> None:
        try:
            self._worker_loop()
        except BaseException:
            # The loop itself died (not a batch — _worker_loop contains
            # per-batch error handling). Requeue what was popped and
            # respawn: queued tenants must survive a worker bug.
            LOG.exception("%s worker died; restarting", self.name)
            with self._lock:
                inflight = self._inflight_by_thread.pop(
                    threading.get_ident(), [])
            self._recover_crashed(inflight)
            self._count("worker_restarts")
            if not self._stop.is_set():
                with self._lock:
                    if self._worker is threading.current_thread():
                        self._worker = None
                self._ensure_worker()

    def _abandoned(self) -> bool:
        """True when THIS thread is no longer the daemon's dispatcher —
        the watchdog replaced it while it was wedged on a hung batch
        (ISSUE 8). The zombie finishes its in-flight no-op demux and
        exits instead of competing with its replacement."""
        return self._worker is not threading.current_thread()

    def _worker_loop(self) -> None:
        """Single-worker mode: form and execute inline (today's loop).
        Multi-worker mode (ISSUE 7): this loop is the DISPATCHER — it
        forms batches and routes each to the least-loaded shard's
        executor, so independent shape buckets run concurrently."""
        tid = threading.get_ident()
        while not self._stop.is_set() and not self._abandoned():
            batch = self.scheduler.next_batch(
                timeout=IDLE_POLL_S, on_decided=self._fastlane_done)
            if not batch:
                continue
            rows = sum(r.n_rows for r in batch)
            if self.n_workers == 1:
                placement = {"shard": 0, "n_shards": 1,
                             "loads_at_dispatch": self.shards.snapshot()}
                self.shards.add(0, rows)
                with self._lock:
                    self._inflight_by_thread[tid] = list(batch)
                try:
                    self._run_batch(batch, placement)
                    # cleared only on NORMAL completion: when execution
                    # kills this thread, the record must survive for
                    # the supervisor's crash recovery to requeue it
                    with self._lock:
                        self._inflight_by_thread.pop(tid, None)
                finally:
                    self.shards.done(0, rows)
                continue
            k = self.shards.least_loaded()
            placement = {"shard": k, "n_shards": self.n_workers,
                         "loads_at_dispatch": self.shards.snapshot()}
            self.shards.add(k, rows)
            if not self._shard_queues[k].put((batch, rows, placement)):
                # Shutdown closed the shard queues between formation
                # and routing: fail the batch loudly, like the drains.
                self.shards.done(k, rows)
                self._fail_unexecuted(batch)

    def _fastlane_done(self, done) -> None:
        """Account requests the dispatch fast lane decided (ISSUE 14):
        they never reach a shard queue or `scheduler.execute`, so the
        completed/latency/cache/tier accounting and trace writes that
        normally ride the batch path run here. The results are clean
        host verdicts (never degraded), so the fingerprint cache and
        cluster store serve resubmissions exactly like batch verdicts."""
        with self._lock:
            self._stats["fastpath_requests"] += len(done)
            for r in done:
                for tier, n in r.stats.get("decided_tier", {}).items():
                    self._tier_counts[tier] = \
                        self._tier_counts.get(tier, 0) + n
        self._account_requests(done)
        for r in done:
            self._write_trace(r)

    def _fail_unexecuted(self, batch) -> None:
        """A shutdown is not a verdict: requests popped from admission
        but never executed fail with the same error the queue drains
        use."""
        for r in batch:
            if r.status in (QUEUED, RUNNING):
                r.finish(FAILED,
                         error="service shut down before execution")
                self._count("failed")
                self._retire(r)

    def _run_batch(self, batch, placement: dict) -> None:
        """Execute one formed batch (dispatcher inline or a shard
        executor): batch-level failures fail only this batch's
        requests; traces are written either way."""
        try:
            info = self.scheduler.execute(batch, placement=placement)
            self._account_batch(batch, info)
        except Exception:
            # Even the host fallback failed (or a scheduler bug):
            # fail THIS batch's requests, keep serving the queue.
            LOG.exception("%s batch execution failed", self.name)
            for r in batch:
                if r.status not in (DONE, CANCELLED, FAILED):
                    r.finish(FAILED, error="batch execution raised; "
                             "see service log")
            self._account_requests(batch)
        for r in batch:
            self._write_trace(r)

    def _supervised_executor(self, k: int) -> None:
        """Shard executor k: drain this shard's routed batches. The
        same survival contract as the dispatcher's supervisor: a dying
        executor requeues its popped-but-unfinished batch into the
        admission queue, bumps ``worker_restarts``, and is respawned —
        queued tenants must survive an executor bug."""
        tid = threading.get_ident()
        try:
            q = self._shard_queues[k]
            while not self._stop.is_set() \
                    and self._executors[k] is threading.current_thread():
                item = q.get(timeout=IDLE_POLL_S)
                if item is None:
                    continue
                batch, rows, placement = item
                with self._lock:
                    self._inflight_by_thread[tid] = list(batch)
                try:
                    self._run_batch(batch, placement)
                    # normal-completion clear only; on death the
                    # supervisor below pops and requeues this record
                    with self._lock:
                        self._inflight_by_thread.pop(tid, None)
                finally:
                    self.shards.done(k, rows)
        except BaseException:
            LOG.exception("%s shard %d executor died; restarting",
                          self.name, k)
            with self._lock:
                inflight = self._inflight_by_thread.pop(tid, [])
            self._recover_crashed(inflight)
            self._count("worker_restarts")
            if not self._stop.is_set():
                with self._lock:
                    if self._executors[k] is threading.current_thread():
                        self._executors[k] = None
                self._ensure_worker()

    def _recover_crashed(self, inflight) -> None:
        """Executor-death recovery with the poison-batch quarantine
        (ISSUE 8). Every unfinished request of the dying batch gets a
        crash strike. Below the cap it re-queues — SPLIT solo when the
        batch had company, so a deterministically-crashing rider re-runs
        alone and its innocent neighbors complete. At the cap
        (JGRAFT_SERVICE_CRASH_CAP, default 2: one batched attempt + one
        solo attempt) the request is FAILED individually — the bounded
        alternative to respawning the worker forever."""
        unfinished = [r for r in inflight
                      if r.status in (QUEUED, RUNNING)]
        survivors = []
        for r in unfinished:
            r.crash_count += 1
            if r.crash_count >= self.crash_cap:
                if r.finish(FAILED, error=(
                        f"quarantined: executor died {r.crash_count}x "
                        "with this request in flight "
                        "(JGRAFT_SERVICE_CRASH_CAP)")):
                    self._count("failed", "quarantined")
                self._retire(r)
                self._write_trace(r)
            else:
                # split: every survivor of a crashed batch is suspect
                # and re-runs SOLO — an innocent rider completes alone,
                # the poison one crashes alone and hits the cap without
                # taking fresh arrivals down with it.
                r.solo = True
                r.status = QUEUED
                survivors.append(r)
        if survivors:
            self.queue.requeue(survivors)

    # ------------------------------------------------------- watchdog

    def _watchdog_loop(self) -> None:
        """Hung-batch watchdog (ISSUE 8): a RUNNING request that blows
        past its deadline by the margin is requeued once (strike one —
        maybe the shard was just busy); past 2x the margin it requeues
        again solo with ``force_host`` set, so the retry runs the
        bounded host ladder and the wedged device launch can never park
        a shard queue forever. The stale execution keeps running — a
        Python thread cannot be killed — but `finish` is first-wins, so
        whichever copy completes first owns the client-visible result;
        the loser demuxes into a no-op."""
        poll = max(0.05, min(1.0, self.watchdog_margin_s / 4.0))
        while not self._stop.wait(poll):
            now = time.monotonic()
            strikes = []
            with self._lock:
                reqs = list(self._requests.values())
            for r in reqs:
                if r.status != RUNNING or r.terminal \
                        or r.cancelled.is_set():
                    continue
                # BOTH clocks must be overdue: the deadline (the
                # client's latency contract) AND the current
                # execution's own runtime. A request that spent its
                # deadline waiting in a backlogged queue is late, not
                # hung — striking it would duplicate work and demote
                # healthy workers exactly when the daemon is busiest
                # (metastable-overload amplification).
                over = min(now - r.deadline, now - r.run_started)
                if over <= self.watchdog_margin_s:
                    continue
                if r.watchdog_hits == 0:
                    r.watchdog_hits = 1
                    strikes.append(r)
                elif (r.watchdog_hits == 1
                        and over > 2.0 * self.watchdog_margin_s):
                    r.watchdog_hits = 2
                    r.solo = True
                    r.force_host = True
                    strikes.append(r)
            for r in strikes:
                # status stays RUNNING on purpose: the wedged execution
                # still holds the request, and strike two keys on that
                # (a watchdog requeue is a retry of running work, not a
                # return to the queued state).
                self._count("watchdog_requeues")
                LOG.warning("%s watchdog: request %s exceeded its "
                            "deadline by >%gs (strike %d%s); requeued",
                            self.name, r.id, self.watchdog_margin_s,
                            r.watchdog_hits,
                            ", forcing host ladder"
                            if r.force_host else "")
                if r.watchdog_hits >= 2:
                    self._abandon_holder(r)
            if strikes:
                self.queue.requeue(strikes)

    def _abandon_holder(self, req: CheckRequest) -> None:
        """De-wedge: the worker thread wedged on `req`'s batch is
        demoted (a Python thread cannot be killed) and a replacement is
        spawned, so the requeued force-host retry — and every later
        batch — has a live worker to run on. The zombie notices it was
        replaced when (if) it unblocks, demuxes into first-wins no-ops,
        and exits its loop."""
        with self._lock:
            holders = [tid for tid, batch
                       in self._inflight_by_thread.items()
                       if any(x is req for x in batch)]
            if not holders:
                return
            for holder in holders:
                if self._worker is not None \
                        and self._worker.ident == holder:
                    self._worker = None
                for k, t in enumerate(self._executors):
                    if t is not None and t.ident == holder:
                        self._executors[k] = None
        LOG.warning("%s watchdog: worker thread(s) %s wedged on request "
                    "%s; spawning replacement", self.name, holders,
                    req.id)
        self._ensure_worker()

    # ------------------------------------------------------ admission

    def submit(self, histories: Sequence, workload: str = "register",
               algorithm: str = "auto", deadline_ms: Optional[float] = None,
               priority: int = 0,
               consistency: str = "linearizable") -> CheckRequest:
        """Admit a submission; returns its CheckRequest (already DONE on
        a cache hit). Raises QueueFull with a retry-after estimate when
        the queue is at capacity, ValueError on malformed input (unknown
        workload/consistency included)."""
        req = admit(histories, workload, algorithm=algorithm,
                    deadline_ms=deadline_ms, priority=priority,
                    consistency=consistency)
        return self._admit(req)

    def submit_frame(self, payload) -> CheckRequest:
        """Admit a binary columnar submission frame (service/frame.py,
        ISSUE 18): the client ran `encode_history` locally, so
        admission decodes zero-copy tensor views, re-derives the
        fingerprint over the received bytes, and skips the encode. The
        WAL already persists ENCODINGS (journal.encode_submit), so the
        frame journals without any re-encode either. Error taxonomy
        matches `submit` (FrameError is a ValueError → 400)."""
        from .admission import admit_frame

        return self._admit(admit_frame(payload))

    def submit_run_dir(self, run_dir, algorithm: str = "auto",
                       deadline_ms: Optional[float] = None,
                       priority: int = 0,
                       workload: Optional[str] = None,
                       consistency: str = "linearizable") -> CheckRequest:
        """Admit a recorded-run directory (store/<name>/<ts>/)."""
        req = admit_run_dir(run_dir, algorithm=algorithm,
                            deadline_ms=deadline_ms, priority=priority,
                            workload=workload, consistency=consistency)
        return self._admit(req)

    def _admit(self, req: CheckRequest) -> CheckRequest:
        if self._stop.is_set():
            # Fast-path refusal; the authoritative (race-free) check is
            # the closed queue's own `put`, below.
            raise ServiceStopped(f"{self.name} is shut down")
        with self._lock:
            self._requests[req.id] = req
        cached = self.cache.get(req.fingerprint)
        if cached is not None and len(cached) == req.n_rows:
            req.cached = True
            req.finish(DONE, results=cached)
            self._count("submitted", "cache_hits", "completed")
            self._observe_latency(req)
            self._retire(req)
            self._write_trace(req)
            return req
        if self.cluster is not None:
            # Shared-store lookup (ISSUE 11): a fingerprint any replica
            # already verified completes here without a kernel launch —
            # the cross-replica cache hit. The LRU is warmed so repeats
            # skip the filesystem too.
            stored = self.cluster.store.get(req.fingerprint)
            if stored is not None and len(stored) == req.n_rows:
                self.cache.put(req.fingerprint, stored)
                req.cached = True
                req.finish(DONE, results=stored)
                self._count("submitted", "store_hits", "completed")
                self._observe_latency(req)
                self._retire(req)
                self._write_trace(req)
                return req
        retry_after = self._retry_after()
        reject: Optional[Exception] = None
        with self._lock:
            # Idempotent resubmission (ISSUE 8): a fingerprint that is
            # already queued/running ATTACHES to the live primary
            # instead of double-checking — the follower completes with
            # the primary's results at `_resolve_followers`. Register /
            # attach and queue-insert happen under ONE lock so a racing
            # duplicate cannot slip between the check and the insert
            # (queue.put's own lock nests safely: on_prune runs outside
            # the queue condition).
            # _journaled is marked BEFORE the request becomes visible
            # to workers: a request fast enough to finish before this
            # thread reaches append_submit below must still get its
            # terminal marker from _retire (replay joins submit and
            # terminal records by id, so their on-disk ORDER is free).
            if self._journal is not None:
                req._journaled = True
            primary = self._primary_by_fp.get(req.fingerprint)
            if primary is not None and not primary.terminal:
                req.attached_to = primary.id
                self._followers.setdefault(primary.id, []).append(req)
                self._stats["submitted"] += 1
                self._stats["attached_requests"] += 1
            else:
                self._primary_by_fp[req.fingerprint] = req
                try:
                    if self.cluster is not None \
                            and self.cluster.should_shed():
                        # past the shed threshold (tentpole (b)): shed
                        # to the cluster with its best retry-after
                        # instead of queueing into a backlog a peer
                        # could absorb now
                        raise QueueFull(self.queue.depth, retry_after)
                    self.queue.put(req, retry_after_s=retry_after)
                except (QueueFull, ServiceStopped) as e:
                    if isinstance(e, QueueFull):
                        self._stats["rejected"] += 1
                    del self._requests[req.id]
                    if self._primary_by_fp.get(req.fingerprint) is req:
                        del self._primary_by_fp[req.fingerprint]
                    reject = e
                else:
                    self._stats["submitted"] += 1
                    self._stats["max_queue_depth"] = max(
                        self._stats["max_queue_depth"], self.queue.depth)
        if reject is not None:
            if isinstance(reject, QueueFull) and self.cluster is not None:
                # A 429 from this replica carries the CLUSTER's best
                # retry-after (min over live leases), so the backed-off
                # client returns when the least-loaded peer has room.
                # Consulting the lease files happens HERE — only on the
                # reject path and outside the daemon lock — never per
                # accepted submission (O(replicas) file reads do not
                # belong on the admission hot path).
                raise QueueFull(
                    reject.depth,
                    self.cluster.best_retry_after(
                        reject.retry_after_s)) from None
            raise reject
        if self._journal is not None:
            # Durability point: the WAL record is fsync'd BEFORE the
            # 202 becomes visible to the client — an accepted request
            # survives SIGKILL from here on. Followers are journaled
            # too (each was individually promised a result).
            self._journal.append_submit(req)
        self._ensure_worker()
        return req

    def _retry_after(self) -> float:
        """Backpressure hint: pending work over observed service rate.
        Never below half a second — a zero would invite a hot retry
        loop from the very client the bound exists to absorb."""
        with self._lock:
            est = self._service_time_s
        return round(max(0.5, self.queue.depth * est), 2)

    # -------------------------------------------------------- queries

    def get(self, request_id: str) -> Optional[CheckRequest]:
        with self._lock:
            return self._requests.get(request_id)

    def cancel(self, request_id: str) -> Optional[str]:
        """Cancel a request: pulled straight out if still queued,
        honored at demux if already riding a launch. Returns the
        request's status, or None for an unknown id."""
        req = self.get(request_id)
        if req is None:
            return None
        req.cancelled.set()
        if self.queue.remove(req):
            if req.finish(CANCELLED):
                self._count("cancelled")
            self._retire(req)
            self._write_trace(req)
        elif req.attached_to is not None:
            # a follower is never in the queue; finalize it directly
            # (first-wins: a racing primary resolution may have beaten
            # the cancel, in which case the delivered result stands)
            if req.finish(CANCELLED):
                self._count("cancelled")
                self._retire(req)
                self._write_trace(req)
        return req.status

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._stats)
            out["decided_tier"] = dict(self._tier_counts)
            lat = list(self._latencies)
        out["queue_depth"] = self.queue.depth
        out["cache_entries"] = len(self.cache)
        out["queue_capacity"] = self.queue.capacity
        out["batch_occupancy_mean"] = round(
            out["batched_requests"] / out["batches"], 3) \
            if out["batches"] else 0.0
        if lat:
            lat.sort()
            out["p50_latency_s"] = round(statistics.median(lat), 4)
            out["p99_latency_s"] = round(
                lat[min(len(lat) - 1, int(0.99 * len(lat)))], 4)
        worker = self._worker
        out["worker_alive"] = bool(worker is not None and worker.is_alive())
        out["workers"] = self.n_workers
        out["shard_loads"] = self.shards.snapshot()
        out["journal_enabled"] = self._journal is not None
        if self._journal is not None:
            out.update(self._journal.stats())
        out["cluster_enabled"] = self.cluster is not None
        if self.cluster is not None:
            out.update(self.cluster.stats())
        out.update(self.streams.stats())
        return out

    # ----------------------------------------------------- accounting

    def _count(self, *keys: str) -> None:
        with self._lock:
            for k in keys:
                if k in self._stats:
                    self._stats[k] += 1

    def _retire(self, req: CheckRequest) -> None:
        """Enter a terminal request into the bounded retention window;
        the oldest finished requests (and their histories/encodings)
        are dropped from the registry past JGRAFT_SERVICE_RETAIN —
        in-flight requests are never evicted (only terminal ids enter
        the window). Also the single terminal choke point for the
        durability tier: the journal's terminal marker is appended here
        (every finish path funnels through _retire), and attached
        followers are resolved with the primary's outcome."""
        with req._finish_lock:
            if getattr(req, "_retired", False):
                return
            req._retired = True
        if self._journal is not None and getattr(req, "_journaled", False):
            self._journal.append_terminal(req)
        self._resolve_followers(req)
        with self._lock:
            self._terminal.append(req.id)
            while len(self._terminal) > self._retain:
                self._requests.pop(self._terminal.popleft(), None)

    def _resolve_followers(self, req: CheckRequest) -> None:
        """Deliver a terminal primary's outcome to its attached
        idempotent duplicates (ISSUE 8). DONE/FAILED mirror onto every
        follower (one execution, many 202s — the at-most-once-execution
        half of idempotent resubmission). A CANCELLED primary must NOT
        cancel its followers (one tenant's cancel is not another's):
        the first live follower is promoted to primary and requeued,
        the rest re-attach to it."""
        with self._lock:
            followers = self._followers.pop(req.id, [])
            if self._primary_by_fp.get(req.fingerprint) is req:
                del self._primary_by_fp[req.fingerprint]
        if not followers:
            return
        if req.status == CANCELLED:
            live = [f for f in followers
                    if not f.terminal and not f.cancelled.is_set()]
            for f in followers:
                if f.cancelled.is_set() and f.finish(CANCELLED):
                    self._count("cancelled")
                    self._retire(f)
                    self._write_trace(f)
            if not live:
                return
            new_primary, rest = live[0], live[1:]
            new_primary.attached_to = None
            with self._lock:
                # setdefault: a fresh submission may have claimed the
                # fingerprint already; then the promoted follower just
                # runs as its own (solo-keyed) primary.
                self._primary_by_fp.setdefault(req.fingerprint,
                                               new_primary)
                for f in rest:
                    f.attached_to = new_primary.id
                    self._followers.setdefault(new_primary.id,
                                               []).append(f)
            self.queue.requeue([new_primary])
            return
        for f in followers:
            if req.status == DONE and req.results is not None:
                done = f.finish(DONE,
                                results=[dict(r) for r in req.results])
                if done:
                    self._count("completed")
                    self._observe_latency(f)
            else:
                if f.finish(FAILED, error=(
                        f"primary request {req.id} "
                        f"{req.status}: {req.error}")):
                    self._count("failed")
            self._retire(f)
            self._write_trace(f)

    def _observe_latency(self, req: CheckRequest) -> None:
        dt = time.monotonic() - req.submitted
        with self._lock:
            self._latencies.append(dt)
            # EWMA feeds the retry-after estimate; per-REQUEST time.
            self._service_time_s = 0.8 * self._service_time_s + 0.2 * dt

    def _account_batch(self, batch, info: dict) -> None:
        with self._lock:
            self._stats["batches"] += 1
            self._stats["batch_rows"] += info["rows"]
            self._stats["batched_requests"] += info["requests"]
            if info["degraded"]:
                self._stats["degraded_batches"] += 1
            for tier, n in info.get("tiers", {}).items():
                self._tier_counts[tier] = \
                    self._tier_counts.get(tier, 0) + n
        self._account_requests(batch)

    def _account_requests(self, batch) -> None:
        for r in batch:
            if not r.terminal:
                # a watchdog-requeued twin of this batch is still
                # running; the copy that finishes will account for it
                continue
            with r._finish_lock:
                if getattr(r, "_accounted", False):
                    # the stale twin of a watchdog requeue demuxed
                    # after the fresh copy already counted this request
                    continue
                r._accounted = True
            if r.status == DONE:
                self._count("completed")
                self._observe_latency(r)
                if not r.stats.get("degraded") and not any(
                        "platform-degraded" in res for res in r.results):
                    # Cache only verdicts free of ANY degrade stamp —
                    # including the process-registry stamp check_encoded
                    # applies (a silently-pinned-CPU process), not just
                    # this scheduler's local degrade path. A cached
                    # stamp would replay onto a healed platform.
                    self.cache.put(r.fingerprint, r.results)
                    # publish fleet-wide (ISSUE 11): the store applies
                    # the same never-persist-degraded rule and is
                    # first-wins against a racing replica
                    if self.cluster is not None and \
                            self.cluster.store.put(r.fingerprint,
                                                   r.results):
                        self._count("store_puts")
            elif r.status == CANCELLED:
                self._count("cancelled")
            elif r.status == FAILED:
                self._count("failed")
            if r.status in (DONE, CANCELLED, FAILED):
                self._retire(r)

    def _finalize_pruned(self, req: CheckRequest) -> None:
        """Queue pruned a cancelled (or already-terminal, e.g. a stale
        watchdog twin) entry before it reached a batch."""
        if req.status not in (DONE, CANCELLED, FAILED):
            if req.finish(CANCELLED):
                self._count("cancelled")
            self._retire(req)
            self._write_trace(req)

    # ---------------------------------------------------------- trace

    def _write_trace(self, req: CheckRequest) -> None:
        """Persist one request's terminal record into the store layout
        (store/<service>/<ts>-<reqid>/: results.json + history.jsonl),
        browsable by `core/serve.py` next to test runs. Best-effort:
        trace IO must never fail a verdict (counted, logged)."""
        if self.store_root is None or req.status == QUEUED:
            return
        try:
            from ..core.store import _jsonable

            ts = time.strftime("%Y%m%dT%H%M%S", time.localtime())
            d = self.store_root / self.name / f"{ts}-{req.id}"
            d.mkdir(parents=True, exist_ok=True)
            payload = _jsonable(req.to_dict())
            # Temp-write + os.replace: core/serve.py can list the run
            # dir mid-write, so the publish must be atomic — a reader
            # never parses a torn results.json. No fsync, though: the
            # trace is best-effort (a power cut may lose it); the
            # authoritative terminal record is the store entry
            # _retire published, which store._publish fsyncs.
            tmp = d / "results.json.tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f, indent=2)
            os.replace(tmp, d / "results.json")
            tmp = d / "history.jsonl.tmp"
            with open(tmp, "w") as f:
                for label, hist in req.units:
                    for op in hist:
                        row = dict(op.to_dict(), unit=label)
                        row_line = json.dumps(_jsonable(row)) + "\n"
                        # best-effort trace: atomic via the replace
                        # below, durability deliberately not promised
                        f.write(row_line)  # lint: allow(fsync)
            os.replace(tmp, d / "history.jsonl")
        except OSError:
            self._count("trace_errors")
            LOG.warning("trace write failed for request %s", req.id,
                        exc_info=True)

"""Write-ahead admission journal: graftd's durability tier (ISSUE 8).

The admission queue is the daemon's only record of accepted work, and it
is in-memory: before this module, a SIGKILL between ``/submit``'s 202
and the verdict silently dropped a request a client was promised a
result for. The journal closes that window with the classic WAL
contract: the ENCODED submission (the same encode-once output the
result-cache fingerprint hashes — service/request.py) is appended and
fsync'd *before* admission returns, a terminal marker is appended when
the request finishes, and on daemon start every submit record without a
terminal marker replays into the admission queue in original deadline
order.

Design points, each load-bearing:

* **Records are JSON lines with a CRC.** Crash mid-append is the NORMAL
  case for a WAL, not an error: the tail of the file may hold a
  truncated line or a torn write. Replay skips corrupt/truncated
  records LOUDLY (logged + counted in ``replayed["skipped"]``) and
  keeps going — one torn tail record must never strand the intact
  entries before it.
* **Terminal records carry clean results.** A DONE marker with a
  verdict free of any ``platform-degraded`` stamp doubles as a
  persisted cache entry: recovery repopulates the fingerprint LRU, so a
  replayed duplicate (or a client's post-restart resubmit) short-
  circuits at admission instead of re-executing — the at-most-once half
  of the exactly-once-verdict argument (doc/checker-design.md §11).
* **Compaction is bounded by ``JGRAFT_SERVICE_RETAIN``.** The WAL of an
  always-on daemon would otherwise grow per request forever. Once the
  finished-pair count exceeds the retention bound, the journal rewrites
  itself keeping every UNFINISHED entry (those are the durability
  payload) plus the newest ``retain`` finished pairs (those are the
  warm-cache payload), via write-temp + ``os.replace`` so a crash
  mid-compaction leaves either the old or the new file, never neither.
* **Journal IO failures degrade durability, not availability.** An
  append that raises OSError is logged and counted
  (``journal_errors``); the request is still admitted. A checking
  daemon that refuses work because its disk hiccuped converts a storage
  fault into an outage — the chaos harness (scripts/chaos_graftd.py)
  injects exactly this and asserts the queue never wedges.

``JGRAFT_SERVICE_JOURNAL=0`` disables the tier entirely — byte-for-byte
today's in-memory daemon (the chaos harness's ablation arm).
"""

from __future__ import annotations

import base64
import json
import logging
import os
import threading
import time
import zlib
from collections import deque
from pathlib import Path
from typing import List, Optional

import numpy as np

from ..history.ops import History
from ..history.packing import EncodedHistory
from ..platform import env_int
from .request import DONE, CheckRequest

LOG = logging.getLogger("jgraft.service")

#: Journal schema version; replay refuses records from a NEWER version
#: loudly (skip + count) instead of misparsing them.
JOURNAL_VERSION = 1

#: Stream-record family version (ISSUE 12). Stream sessions put MANY
#: records under one session id (open / per-segment / fin) — a distinct
#: record family from the submit/terminal pairs, versioned separately so
#: the streaming wire format can evolve without bumping the whole WAL
#: schema: replay skips NEWER stream records loudly while still
#: replaying every request record, and a pre-PR-12 WAL (no stream
#: records at all) replays byte-for-byte as before (the forward-compat
#: fixture test in tests/test_stream.py pins both directions).
#: v2 (ISSUE 18): adds the ``stream-bseg`` kind — binary-lane segments
#: journal the client-settled ARRAYS instead of raw op dicts. An old
#: daemon skips v2 records loudly (fail-safe: a WAL holding binary
#: segments is not replayable by a daemon that cannot decode them).
STREAM_VERSION = 2

#: The stream record kinds (`kind` field values).
STREAM_KINDS = ("stream-open", "stream-seg", "stream-bseg", "stream-fin")

#: Appends timed for the bench's admission-overhead evidence
#: (`journal_append_p50_ms` in `bench.py --service` rows).
APPEND_WINDOW = 4096

#: Default group-commit linger (ms). See `journal_group_ms`.
DEFAULT_GROUP_MS = 2


def journal_enabled() -> bool:
    """JGRAFT_SERVICE_JOURNAL gate (default on; 0 restores the
    in-memory-only daemon — defensively parsed like every env gate)."""
    return env_int("JGRAFT_SERVICE_JOURNAL", 1, minimum=0) != 0


def journal_group_ms() -> int:
    """Group-commit linger window in ms (ISSUE 15 tentpole (c)).

    With N concurrent appenders, per-append fsync serializes into a
    lock convoy (measured: solo fsync ~0.15 ms on this host, but
    `journal_append_p50_ms` 6.5 ms under the bench's 8 clients).
    Group commit coalesces: one appender becomes the LEADER, writes
    every queued record, and issues ONE fsync covering the whole
    group; each member's append returns only after THAT fsync — the
    §11 durability point (no 2xx before the fsync covering *your*
    record) is preserved exactly, because membership in the group is
    decided before the write and completion is signalled after the
    fsync returns. The linger (up to this window, waiting for riders)
    is adaptive — it engages only while recent groups actually carried
    riders, so an uncontended appender pays no added latency
    (`_append_grouped`).

    ``JGRAFT_JOURNAL_GROUP_MS=0`` restores today's exact per-append
    write+fsync behavior (the same-process A/B arm). Resolved per
    append so the bench can flip arms against one live daemon."""
    return env_int("JGRAFT_JOURNAL_GROUP_MS", DEFAULT_GROUP_MS,
                   minimum=0)


def _b64(arr: np.ndarray) -> str:
    return base64.b64encode(
        np.ascontiguousarray(arr, dtype=np.int32).tobytes()).decode("ascii")


def _unb64(s: str, shape) -> np.ndarray:
    return np.frombuffer(base64.b64decode(s.encode("ascii")),
                         dtype=np.int32).reshape(shape).copy()


def _crc_line(rec: dict) -> str:
    """Canonical CRC32 over the record minus its own crc field."""
    body = {k: v for k, v in rec.items() if k != "crc"}
    canon = json.dumps(body, sort_keys=True, separators=(",", ":"))
    return format(zlib.crc32(canon.encode()), "08x")


def encode_submit(req: CheckRequest) -> dict:
    """Submit record: everything replay needs to rebuild the request —
    the per-unit ENCODINGS (authoritative checker input; the raw op
    dicts are deliberately not journaled, so a replayed request's trace
    record has an empty history.jsonl), scheduling metadata converted
    to WALL time (monotonic clocks do not survive a restart), and the
    fingerprint (idempotency key)."""
    now_mono, now_wall = time.monotonic(), time.time()
    return {
        "kind": "submit",
        "v": JOURNAL_VERSION,
        "id": req.id,
        "workload": req.workload,
        "model": type(req.model).__name__,
        "algorithm": req.algorithm,
        "consistency": req.consistency,
        "fingerprint": req.fingerprint,
        "priority": req.priority,
        "deadline_wall": now_wall + (req.deadline - now_mono),
        "submitted_wall": now_wall - (now_mono - req.submitted),
        "units": [{
            "label": label,
            "n_slots": enc.n_slots,
            "n_ops": enc.n_ops,
            "events_shape": list(enc.events.shape),
            "events": _b64(enc.events),
            "op_index": _b64(enc.op_index),
            # proc rides along when present: the weaker-consistency
            # rungs relax along per-process order, and a replayed
            # request must reach the same relaxed stream (a missing
            # proc degrades the rung to the conservative identity
            # relaxation — sound, but stricter than promised).
            **({"proc": _b64(enc.proc)} if enc.proc is not None else {}),
        } for (label, _), enc in zip(req.units, req.encs)],
    }


def encode_terminal(req: CheckRequest) -> dict:
    """Terminal marker. Results ride along only for a clean DONE (the
    same never-persist-degraded rule the LRU cache applies): a degraded
    stamp describes the run that produced it, not a future replay."""
    rec = {
        "kind": "terminal",
        "v": JOURNAL_VERSION,
        "id": req.id,
        "fingerprint": req.fingerprint,
        "status": req.status,
    }
    if req.error is not None:
        rec["error"] = str(req.error)[:500]
    if req.status == DONE and req.results is not None and not any(
            "platform-degraded" in r for r in req.results):
        from ..core.store import _jsonable

        rec["results"] = _jsonable(req.results)
    return rec


def decode_request(rec: dict) -> CheckRequest:
    """Rebuild a CheckRequest from a submit record. Wall-clock deadline
    and submit time are mapped back onto THIS process's monotonic clock,
    preserving both the original deadline ORDER across replayed entries
    and the aging credit already accrued before the crash."""
    from .. import models as _models

    model_cls = getattr(_models, rec["model"], None)
    if model_cls is None:
        raise ValueError(f"journal record {rec['id']}: unknown model "
                         f"{rec['model']!r}")
    now_mono, now_wall = time.monotonic(), time.time()
    units, encs = [], []
    for u in rec["units"]:
        events = _unb64(u["events"], u["events_shape"])
        op_index = _unb64(u["op_index"], (u["events_shape"][0],))
        proc = (_unb64(u["proc"], (u["events_shape"][0],))
                if u.get("proc") is not None else None)
        units.append((u["label"], History()))
        encs.append(EncodedHistory(events=events, op_index=op_index,
                                   n_slots=int(u["n_slots"]),
                                   n_ops=int(u["n_ops"]), proc=proc))
    return CheckRequest(
        id=rec["id"],
        workload=rec["workload"],
        model=model_cls(),
        algorithm=rec["algorithm"],
        consistency=rec.get("consistency", "linearizable"),
        units=units,
        encs=encs,
        fingerprint=rec["fingerprint"],
        deadline=now_mono + (float(rec["deadline_wall"]) - now_wall),
        submitted=now_mono - max(0.0, now_wall
                                 - float(rec["submitted_wall"])),
        priority=int(rec["priority"]),
        replayed=True,
    )


def encode_stream_open(sid: str, workload: str, model_name: str,
                       algorithm: str, consistency: str,
                       n_units: int) -> dict:
    """Stream session-open record (ISSUE 12)."""
    return {
        "kind": "stream-open",
        "v": JOURNAL_VERSION,
        "stream_v": STREAM_VERSION,
        "sid": sid,
        "workload": workload,
        "model": model_name,
        "algorithm": algorithm,
        "consistency": consistency,
        "units": int(n_units),
        "opened_wall": time.time(),
    }


def encode_stream_segment(sid: str, seq: int, unit_ops, digest: str) -> dict:
    """One appended segment: the RAW op dict rows per unit (replay
    re-feeds them through the same incremental encoder the live path
    used, so the rebuilt carry is deterministic), plus the payload
    digest duplicate-detection keys on."""
    return {
        "kind": "stream-seg",
        "v": JOURNAL_VERSION,
        "stream_v": STREAM_VERSION,
        "sid": sid,
        "seq": int(seq),
        "digest": digest,
        "ops": unit_ops,
    }


def encode_stream_bseg(sid: str, seq: int, units, digest: str) -> dict:
    """One binary-lane segment (ISSUE 18): the client-settled suffix
    ARRAYS per unit plus the client encoder's cumulative counters —
    there are no raw op dicts to journal on this lane, and replay feeds
    the arrays straight back (`StreamSession.append_binary`) instead of
    re-encoding. Unit dicts are the `frame.SegmentFrame` payload shape:
    ``{"events", "op_index", "proc" (array|None), "n_slots", "n_ops",
    "consumed", "final"}``."""
    return {
        "kind": "stream-bseg",
        "v": JOURNAL_VERSION,
        "stream_v": STREAM_VERSION,
        "sid": sid,
        "seq": int(seq),
        "digest": digest,
        "units": [{
            "n_events": int(np.asarray(u["events"]).reshape(-1, 5).shape[0]),
            "n_slots": int(u["n_slots"]),
            "n_ops": int(u["n_ops"]),
            "consumed": int(u["consumed"]),
            "final": bool(u.get("final", False)),
            "events": _b64(np.asarray(u["events"]).reshape(-1, 5)),
            "op_index": _b64(u["op_index"]),
            **({"proc": _b64(u["proc"])}
               if u.get("proc") is not None else {}),
        } for u in units],
    }


def decode_stream_bseg_units(rec: dict) -> List[dict]:
    """Rebuild a ``stream-bseg`` record's per-unit payload dicts (the
    same shape `append_binary` consumes live). Malformed payloads raise
    ValueError/KeyError — the caller (session rebuild) skips loudly."""
    out: List[dict] = []
    for u in rec["units"]:
        n = int(u["n_events"])
        out.append({
            "events": _unb64(u["events"], (n, 5)),
            "op_index": _unb64(u["op_index"], (n,)),
            "proc": (_unb64(u["proc"], (n,))
                     if u.get("proc") is not None else None),
            "n_slots": int(u["n_slots"]),
            "n_ops": int(u["n_ops"]),
            "consumed": int(u["consumed"]),
            "final": bool(u.get("final", False)),
        })
    return out


def encode_stream_fin(sid: str, status: str, results=None,
                      error=None) -> dict:
    """Terminal marker for a stream session. Results ride along for a
    clean finish (same never-persist-degraded rule as request
    terminals) so `/stream/status` answers across a restart without a
    rebuild."""
    rec = {
        "kind": "stream-fin",
        "v": JOURNAL_VERSION,
        "stream_v": STREAM_VERSION,
        "sid": sid,
        "status": status,
    }
    if error is not None:
        rec["error"] = str(error)[:500]
    if results is not None and not any(
            isinstance(r, dict) and "platform-degraded" in r
            for r in results):
        from ..core.store import _jsonable

        rec["results"] = _jsonable(results)
    return rec


class AdmissionJournal:
    """Append-only WAL at ``<root>/wal.jsonl`` (root is
    ``store/<service>/journal/`` in the daemon's layout)."""

    def __init__(self, root, retain: Optional[int] = None):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.path = self.root / "wal.jsonl"
        self.retain = (retain if retain is not None
                       else env_int("JGRAFT_SERVICE_RETAIN", 1024,
                                    minimum=1))
        self._lock = threading.Lock()
        self._fh = None  # guarded_by(_lock)
        self._errors = 0  # guarded_by(_lock)
        self._appends = 0  # guarded_by(_lock)
        # group commit (ISSUE 15): pending entries + leader election.
        # _gcond guards _gqueue/_gleader; the IO itself runs under
        # _lock like every other write, so compaction/stats never
        # interleave with a group's write+fsync.
        self._gcond = threading.Condition(threading.Lock())
        # [line, done, ok] per entry
        self._gqueue: List[list] = []  # guarded_by(_gcond)
        self._gleader = False  # guarded_by(_gcond)
        self._glast_multi = False   # previous group carried riders?
        self._group_commits = 0
        self._group_records = 0
        # Seeded lazily by replay() (which scans the file anyway — a
        # dedicated counting scan at open would read and CRC-check the
        # whole WAL a second time for nothing); a journal used without
        # a replay just starts the compaction amortization from zero.
        self._finished_since_compact = 0
        self.append_ms: deque = deque(maxlen=APPEND_WINDOW)

    # ------------------------------------------------------------ write

    def _handle(self):  # requires(_lock)
        if self._fh is None or self._fh.closed:
            self._fh = open(self.path, "ab")
        return self._fh

    def _append(self, rec: dict, fsync: bool) -> bool:
        rec["crc"] = _crc_line(rec)
        line = (json.dumps(rec, sort_keys=True,
                           separators=(",", ":")) + "\n").encode()
        group = journal_group_ms() if fsync else 0
        if group > 0:
            return self._append_grouped(line, rec, group)
        t0 = time.perf_counter()
        try:
            with self._lock:
                fh = self._handle()
                fh.write(line)
                fh.flush()
                if fsync:
                    os.fsync(fh.fileno())
                # counters under the same lock: stats() iterates
                # append_ms while holding it (a bare deque.append is
                # atomic, but sorted() mid-mutation is not)
                self._appends += 1
                self.append_ms.append(
                    (time.perf_counter() - t0) * 1000.0)
        except OSError:
            # Durability degraded, availability kept: the daemon counts
            # and logs, the request is still served (module docstring).
            with self._lock:
                self._errors += 1
            LOG.warning("journal append failed for %s record %s",
                        rec.get("kind"), rec.get("id"), exc_info=True)
            return False
        return True

    def _append_grouped(self, line: bytes, rec: dict,
                        group_ms: int) -> bool:
        """Leader/follower group commit (`journal_group_ms`). The
        caller's entry joins the pending queue; the first appender with
        no leader in flight LEADS: it drains the queue, writes every
        line, and issues ONE fsync for the whole group. Every member
        (leader included) returns only after the fsync that covers ITS
        line — the §11 durability point, unchanged. A failed group
        write degrades durability for all members (counted per record,
        availability kept) exactly like the per-append path.

        The linger is ADAPTIVE: a solo leader sleeps up to ``group_ms``
        for riders only when the PREVIOUS group carried some (an
        in-flight-contention signal); an uncontended appender commits
        immediately, so solo-append latency is identical to the
        per-append path. Under real concurrency no sleep is needed at
        all — followers pile into the queue during the current group's
        write+fsync and the next leader finds them already waiting."""
        t0 = time.perf_counter()
        entry = [line, False, False]   # line, done, ok
        with self._gcond:
            self._gqueue.append(entry)
            while not entry[1] and self._gleader:
                self._gcond.wait(0.05)
            lead = not entry[1]
            if lead:
                self._gleader = True
                linger = (len(self._gqueue) == 1 and self._glast_multi)
        if lead:
            batch: List[list] = []
            ok = False
            try:
                if group_ms and linger:
                    time.sleep(group_ms / 1000.0)   # linger for riders
                with self._gcond:
                    batch = self._gqueue
                    self._gqueue = []
                try:
                    with self._lock:
                        fh = self._handle()
                        fh.write(b"".join(e[0] for e in batch))
                        fh.flush()
                        os.fsync(fh.fileno())
                        self._appends += len(batch)
                        self._group_commits += 1
                        self._group_records += len(batch)
                    ok = True
                except OSError:
                    with self._lock:
                        self._errors += len(batch)
                    LOG.warning("journal group append failed "
                                "(%d records)", len(batch),
                                exc_info=True)
            finally:
                with self._gcond:
                    for e in batch:
                        e[2] = ok
                        e[1] = True
                    self._gleader = False
                    self._glast_multi = len(batch) > 1
                    self._gcond.notify_all()
        with self._lock:
            self.append_ms.append((time.perf_counter() - t0) * 1000.0)
        return entry[2]

    def append_submit(self, req: CheckRequest) -> bool:
        """Durability point: returns only after the record is fsync'd
        (or after the failure was counted). Must be called BEFORE the
        202 is visible to the client."""
        return self._append(encode_submit(req), fsync=True)

    def append_terminal(self, req: CheckRequest) -> bool:
        """Mark a journaled request finished. fsync'd too — a lost
        terminal marker is only re-execution on replay (idempotent),
        but a persisted one is a warm cache entry worth the write."""
        ok = self._append(encode_terminal(req), fsync=True)
        with self._lock:
            self._finished_since_compact += 1
            # amortized: compact once the WAL holds ~2x the retention
            # bound of finished pairs (each compaction trims back to
            # `retain`, so the file oscillates between retain and
            # 2·retain pairs instead of rewriting per append)
            should = self._finished_since_compact > 2 * self.retain
        if should:
            self.compact()
        return ok

    def append_stream(self, rec: dict) -> bool:
        """Append one stream-family record (open/segment/fin), fsync'd —
        the append path's 2xx must not become visible before the segment
        is durable (ISSUE 12). Same degrade-not-refuse stance as every
        other append."""
        ok = self._append(rec, fsync=True)
        if rec.get("kind") == "stream-fin":
            with self._lock:
                self._finished_since_compact += 1
                should = self._finished_since_compact > 2 * self.retain
            if should:
                self.compact()
        return ok

    # ----------------------------------------------------------- replay

    def _scan(self):
        """(records, skipped): parsed records in file order; corrupt or
        truncated lines are skipped LOUDLY — a torn tail is the normal
        crash signature, and it must cost one record, not the file."""
        records: List[dict] = []
        skipped = 0
        try:
            raw = self.path.read_bytes()
        except FileNotFoundError:
            return records, skipped
        for ln, line in enumerate(raw.split(b"\n"), 1):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
                if not isinstance(rec, dict):
                    raise ValueError("journal line is not an object")
                if int(rec.get("v", -1)) > JOURNAL_VERSION:
                    raise ValueError(
                        f"record version {rec.get('v')} is newer than "
                        f"this daemon ({JOURNAL_VERSION})")
                if rec.get("crc") != _crc_line(rec):
                    raise ValueError("crc mismatch (torn write)")
            except (ValueError, json.JSONDecodeError) as e:
                skipped += 1
                LOG.warning("journal %s line %d skipped: %s",
                            self.path, ln, e)
                continue
            records.append(rec)
        return records, skipped

    def replay(self) -> dict:
        """Join submits with their terminal markers. Returns::

            {"unfinished": [CheckRequest…]   # deadline order
             "finished":   [(submit_rec, terminal_rec)…],
             "streams":    {sid: {"open": rec, "segments": [rec…],
                                  "fin": rec | None}},
             "skipped":    int}              # corrupt/truncated lines

        Submit records that fail to DECODE (unknown model, mangled
        tensor payload) are skipped loudly like torn lines — replay
        must deliver every intact entry even when one is poison.
        Stream records (ISSUE 12) are their OWN record family — many
        records per session id, versioned by ``stream_v`` — grouped
        per session; a record from a NEWER stream version is skipped
        loudly without touching the request replay, and a WAL with no
        stream records (pre-PR-12) replays exactly as before."""
        records, skipped = self._scan()
        submits = {}
        terminals = {}
        streams: dict = {}
        for rec in records:
            kind = rec.get("kind")
            if kind == "submit":
                submits[rec["id"]] = rec
            elif kind == "terminal":
                terminals[rec["id"]] = rec
            elif kind in STREAM_KINDS:
                try:
                    if int(rec.get("stream_v", -1)) > STREAM_VERSION:
                        raise ValueError(
                            f"stream record version {rec.get('stream_v')} "
                            f"is newer than this daemon ({STREAM_VERSION})")
                    sid = str(rec["sid"])
                except (ValueError, KeyError, TypeError) as e:
                    skipped += 1
                    LOG.warning("journal stream record skipped: %s", e)
                    continue
                s = streams.setdefault(
                    sid, {"open": None, "segments": [], "fin": None})
                if kind == "stream-open":
                    s["open"] = rec
                elif kind == "stream-fin":
                    s["fin"] = rec
                else:
                    s["segments"].append(rec)
        # Orphaned segments (their open record was corrupt/compacted
        # away) cannot rebuild a session: skipped loudly, not silently.
        for sid in [k for k, s in streams.items() if s["open"] is None]:
            skipped += len(streams[sid]["segments"])
            LOG.warning("journal stream %s has segments but no open "
                        "record; session dropped", sid)
            del streams[sid]
        for s in streams.values():
            # duplicate seqs are first-wins (a retried append whose 2xx
            # was lost journals twice; the payloads are digest-equal)
            seen: dict = {}
            for rec in s["segments"]:
                seen.setdefault(int(rec.get("seq", -1)), rec)
            s["segments"] = [seen[k] for k in sorted(seen)]
        unfinished: List[CheckRequest] = []
        finished = []
        for rid, rec in submits.items():
            if rid in terminals:
                finished.append((rec, terminals[rid]))
                continue
            try:
                unfinished.append(decode_request(rec))
            except (ValueError, KeyError, TypeError) as e:
                skipped += 1
                LOG.warning("journal entry %s undecodable, skipped: %s",
                            rid, e)
        unfinished.sort(key=lambda r: (r.deadline, r.submitted))
        with self._lock:
            # replay doubles as the finished-pair census that seeds the
            # compaction trigger (no separate counting scan at open)
            self._finished_since_compact = len(finished) + sum(
                1 for s in streams.values() if s["fin"] is not None)
        return {"unfinished": unfinished, "finished": finished,
                "streams": streams, "skipped": skipped}

    def stream_records(self, sid: str) -> Optional[dict]:
        """Re-scan the WAL for ONE session's stream records (the revive
        path of a parked/restored session — parking drops the records
        from memory on purpose; a revive pays one file scan, and never
        the tensor decode `replay()` does for request records). Returns
        the same per-session dict `replay()["streams"]` holds, or None
        when the session has no (intact) open record."""
        sid = str(sid)
        records, _ = self._scan()
        out = {"open": None, "segments": [], "fin": None}
        seen: dict = {}
        for rec in records:
            kind = rec.get("kind")
            if kind not in STREAM_KINDS or str(rec.get("sid")) != sid:
                continue
            try:
                if int(rec.get("stream_v", -1)) > STREAM_VERSION:
                    continue  # replay() already logged these
            except (ValueError, TypeError):
                continue
            if kind == "stream-open":
                out["open"] = rec
            elif kind == "stream-fin":
                out["fin"] = rec
            else:
                seen.setdefault(int(rec.get("seq", -1)), rec)
        out["segments"] = [seen[k] for k in sorted(seen)]
        return out if out["open"] is not None else None

    # ------------------------------------------------------- compaction

    def compact(self) -> None:
        """Rewrite the WAL: every unfinished entry survives, only the
        newest `retain` finished pairs do. Atomic via temp+replace —
        a crash mid-compaction leaves a valid journal either way.

        Stream sessions (ISSUE 12) follow the same rule in their own
        family: an UNFINISHED session keeps every record (open + all
        segments — that is the resumability payload), a finished one
        keeps only its open+fin pair (status stays queryable across a
        restart; the segment payloads are dead weight once a terminal
        verdict exists), bounded to the newest `retain` finished
        sessions."""
        with self._lock:
            records, _ = self._scan()
            terminals = {r["id"]: r for r in records
                         if r.get("kind") == "terminal"}
            stream_fins = {str(r.get("sid")): r for r in records
                           if r.get("kind") == "stream-fin"}
            # finished sessions, oldest first (fin record file order)
            fin_order = list(stream_fins)
            drop_fins = set(fin_order[:-self.retain]
                            if self.retain else fin_order)
            keep: List[dict] = []
            finished_pairs = []
            for rec in records:
                kind = rec.get("kind")
                if kind in STREAM_KINDS:
                    sid = str(rec.get("sid"))
                    if sid not in stream_fins:
                        keep.append(rec)      # unfinished: keep whole
                    elif (kind not in ("stream-seg", "stream-bseg")
                          and sid not in drop_fins):
                        keep.append(rec)      # finished: open+fin only
                    continue
                if kind != "submit":
                    continue
                term = terminals.get(rec["id"])
                if term is None:
                    keep.append(rec)
                else:
                    finished_pairs.append((rec, term))
            for sub, term in finished_pairs[-self.retain:]:
                keep.extend((sub, term))
            tmp = self.path.with_suffix(".jsonl.tmp")
            try:
                with open(tmp, "wb") as fh:
                    for rec in keep:
                        fh.write((json.dumps(
                            rec, sort_keys=True,
                            separators=(",", ":")) + "\n").encode())
                    fh.flush()
                    os.fsync(fh.fileno())
                if self._fh is not None and not self._fh.closed:
                    self._fh.close()
                os.replace(tmp, self.path)
            except OSError:
                self._errors += 1
                LOG.warning("journal compaction failed; keeping the "
                            "uncompacted WAL", exc_info=True)
                return
            self._finished_since_compact = (
                min(len(finished_pairs), self.retain)
                + len(stream_fins) - len(drop_fins))

    # ------------------------------------------------------------ stats

    def stats(self) -> dict:
        with self._lock:
            samples = sorted(self.append_ms)
            out = {
                "journal_appends": self._appends,
                "journal_errors": self._errors,
                # group-commit evidence (ISSUE 15): how many fsyncs the
                # WAL actually issued and how many records each covered
                "journal_group_ms": journal_group_ms(),
                "journal_group_commits": self._group_commits,
                "journal_group_occupancy_mean": round(
                    self._group_records / self._group_commits, 3)
                if self._group_commits else 0.0,
            }
        if samples:
            out["journal_append_p50_ms"] = round(
                samples[len(samples) // 2], 4)
        return out

    def close(self) -> None:
        with self._lock:
            if self._fh is not None and not self._fh.closed:
                self._fh.close()

"""In-process linearizable SUT with fault injection.

A single-copy state machine (register map / counter / leadership record)
applied under one lock — trivially linearizable, so any checker failure
against it is a checker bug, and any injected fault produces an *honest*
history (a timed-out op really may apply later: it keeps executing on the
server executor after the client gives up — the same indefinite semantics
the reference gets from real networks, workload/client.clj:52-63).

Connection API is shaped like the reference's sync clients
(SyncReplicatedStateMachineClient / SyncReplicatedCounterClient /
SyncLeaderInspectionClient — SURVEY.md §2.2 J7-J9), so workloads run
unchanged against this or the native TCP tier.

Fault hooks (driven by nemeses or latency plans):
  * kill(node)/restart(node)  — connections to killed nodes refuse
    (definite errors).
  * pause(node)/resume(node)  — ops through paused nodes block until
    resume (client times out; op applies on resume → indefinite).
  * partition(grudge)/heal()  — ops through nodes that cannot reach a
    quorum block until heal (client times out; op applies on heal →
    indefinite); leadership moves to the quorum side; isolated nodes
    report a stale leader/term view until healed.
  * add_node/remove_node      — runtime membership change (the member
    nemesis, reference nemesis/membership.clj).
  * latency spikes            — LatencyPlan.slow_prob makes ops exceed the
    client timeout while still applying.
"""

from __future__ import annotations

import random
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as FutTimeout
from dataclasses import dataclass
from typing import Optional, Tuple

from ..client.errors import ClientTimeout, ConnectFailed


@dataclass
class LatencyPlan:
    base: float = 0.0002       # fixed per-op latency (s)
    jitter: float = 0.0003     # mean of added exponential jitter
    slow_prob: float = 0.0     # chance of a timeout-inducing stall
    slow_s: float = 0.5        # stall duration
    seed: Optional[int] = None


class InMemoryCluster:
    def __init__(self, nodes, latency: Optional[LatencyPlan] = None,
                 initial_leader: Optional[str] = None):
        self.nodes = list(nodes)
        self.plan = latency or LatencyPlan()
        self.rng = random.Random(self.plan.seed)
        self.lock = threading.Lock()
        self.map: dict = {}
        self.counters: dict = {}
        self.term = 1
        self.leader = initial_leader or self.nodes[0]
        self.killed: set = set()
        self.resume_events = {n: threading.Event() for n in self.nodes}
        for e in self.resume_events.values():
            e.set()  # not paused
        #: node -> set of nodes it cannot talk to (symmetric by contract).
        self.grudge: dict = {}
        #: isolated node -> (leader, term) snapshot from partition time.
        self.stale_views: dict = {}
        self.heal_event = threading.Event()
        self.heal_event.set()  # not partitioned
        self.pool = ThreadPoolExecutor(max_workers=64,
                                       thread_name_prefix="sut")
        self.closed = False

    # ---- fault hooks (the nemesis side) ---------------------------------

    def kill(self, node: str) -> None:
        with self.lock:
            self.killed.add(node)
            if self.leader == node:
                self._elect_locked()

    def restart(self, node: str) -> None:
        with self.lock:
            self.killed.discard(node)
            if self.leader is None:
                self._elect_locked()

    def pause(self, node: str) -> None:
        self.resume_events[node].clear()
        if self.leader == node:
            with self.lock:
                self._elect_locked()

    def resume(self, node: str) -> None:
        self.resume_events[node].set()

    def partition(self, grudge: dict) -> None:
        """Install a grudge map (node -> unreachable peers). A node that can
        no longer see a quorum cannot commit: its ops block until heal. If
        the leader lost quorum, the quorum side elects a new leader; nodes
        without quorum keep serving their pre-partition leader/term view
        (stale, but election-safe: old term -> old leader)."""
        with self.lock:
            snapshot = (self.leader, self.term)
            self.grudge = {n: set(g) for n, g in grudge.items()}
            self.stale_views = {
                n: snapshot for n in self.nodes if not self._quorum_locked(n)
            }
            if self.leader is not None and self.leader in self.stale_views:
                self._elect_locked()
            if self.stale_views:
                self.heal_event.clear()
            elif self.grudge:
                # Every node retains quorum (e.g. majorities-ring): no
                # availability change, but the disruption still triggers
                # an election round, like a real view change would.
                self._elect_locked()

    def heal(self) -> None:
        with self.lock:
            self.grudge = {}
            self.stale_views = {}
            if self.leader is None:
                self._elect_locked()
        self.heal_event.set()

    def add_node(self, node: str) -> None:
        with self.lock:
            if node in self.nodes:
                return
            self.nodes.append(node)
            # Reuse any prior Event: a server thread from the node's
            # earlier life may still be blocked on it, and replacing the
            # object would strand that thread beyond resume/shutdown.
            ev = self.resume_events.setdefault(node, threading.Event())
            ev.set()

    def remove_node(self, node: str) -> None:
        with self.lock:
            if node not in self.nodes:
                return
            self.nodes.remove(node)
            self.killed.discard(node)
            self.stale_views.pop(node, None)
            if self.leader == node:
                self._elect_locked()

    def _quorum_locked(self, node: str) -> bool:
        """Can `node` reach a strict majority of the current membership
        (itself included) under the installed grudge?"""
        n = len(self.nodes)
        visible = [m for m in self.nodes
                   if m == node or m not in self.grudge.get(node, ())]
        return len(visible) > n // 2

    def _elect_locked(self) -> None:
        alive = [n for n in self.nodes
                 if n not in self.killed and self.resume_events[n].is_set()
                 and self._quorum_locked(n)]
        self.term += 1
        self.leader = self.rng.choice(alive) if alive else None

    def elect(self, node: Optional[str] = None) -> None:
        with self.lock:
            if node is None:
                self._elect_locked()
            else:
                self.term += 1
                self.leader = node

    def shutdown(self) -> None:
        self.closed = True
        for e in self.resume_events.values():
            e.set()
        self.heal_event.set()
        self.pool.shutdown(wait=False, cancel_futures=True)

    # ---- server side ----------------------------------------------------

    def _apply(self, node: str, fn, local: bool):
        """Simulated server-side execution: latency, pause gate, partition
        gate (consensus ops only), then the linearization point under the
        cluster lock. `local` ops (leader inspection, dirty reads) skip the
        quorum requirement — they answer from node-local state, like the
        reference's LeaderElection SM (SURVEY.md J5) and dirty reads."""
        d = self.plan.base + self.rng.expovariate(1.0 / self.plan.jitter) \
            if self.plan.jitter > 0 else self.plan.base
        if self.plan.slow_prob and self.rng.random() < self.plan.slow_prob:
            d += self.plan.slow_s
        time.sleep(d)
        ev = self.resume_events.get(node)
        if ev is not None:
            ev.wait()
        while True:
            if self.closed:
                raise ConnectFailed("cluster shut down")
            with self.lock:
                if node in self.killed:
                    raise ConnectFailed(f"{node} is down")
                if node not in self.nodes:
                    raise ConnectFailed(f"{node} is not a member")
                if local or self._quorum_locked(node):
                    return fn()
            # No quorum: the op waits out the partition (the client will
            # time out; the op applies on heal — honest indefiniteness).
            self.heal_event.wait(timeout=0.05)

    def submit(self, node: str, fn, timeout: float, local: bool = False):
        if self.closed:
            raise ConnectFailed("cluster shut down")
        if node in self.killed:
            raise ConnectFailed(f"{node} is down")
        fut = self.pool.submit(self._apply, node, fn, local)
        try:
            return fut.result(timeout)
        except FutTimeout:
            # The op keeps running server-side: honest indefiniteness.
            raise ClientTimeout(f"no response from {node} in {timeout}s")

    # ---- client connections (J7/J8/J9-shaped) ---------------------------

    def conn(self, node: str, kind: str, timeout: float = 5.0):
        if kind == "register":
            return RsmConn(self, node, timeout)
        if kind == "counter":
            return CounterConn(self, node, timeout)
        if kind == "election":
            return LeaderConn(self, node, timeout)
        raise ValueError(f"unknown connection kind {kind!r}")


class _Conn:
    def __init__(self, cluster: InMemoryCluster, node: str, timeout: float):
        self.cluster = cluster
        self.node = node
        self.timeout = timeout

    def _do(self, fn, local: bool = False):
        return self.cluster.submit(self.node, fn, self.timeout, local=local)

    def close(self) -> None:
        pass


class RsmConn(_Conn):
    """Replicated-map connection: put / quorum-or-dirty get / cas."""

    def put(self, key, value) -> None:
        self._do(lambda: self.cluster.map.__setitem__(key, value))

    def get(self, key, quorum: bool = True):
        # Single-copy: a dirty read returns the same value as a quorum
        # read, but it skips the quorum gate — so it still answers from a
        # partition-isolated node, like the native tier's local reads.
        return self._do(lambda: self.cluster.map.get(key), local=not quorum)

    def cas(self, key, frm, to) -> bool:
        def go():
            if self.cluster.map.get(key) == frm:
                self.cluster.map[key] = to
                return True
            return False
        return self._do(go)


class CounterConn(_Conn):
    """Counter connection; one named counter, like the reference client's
    fixed "mtc" (SyncReplicatedCounterClient, SURVEY.md J8)."""

    name = "mtc"

    def get(self) -> int:
        return self._do(lambda: self.cluster.counters.get(self.name, 0))

    def add(self, delta: int) -> None:
        def go():
            self.cluster.counters[self.name] = (
                self.cluster.counters.get(self.name, 0) + delta)
        self._do(go)

    def add_and_get(self, delta: int) -> int:
        def go():
            v = self.cluster.counters.get(self.name, 0) + delta
            self.cluster.counters[self.name] = v
            return v
        return self._do(go)

    def cas(self, expect: int, update: int) -> bool:
        def go():
            if self.cluster.counters.get(self.name, 0) == expect:
                self.cluster.counters[self.name] = update
                return True
            return False
        return self._do(go)


class LeaderConn(_Conn):
    """Leadership inspection: (leader, term) as observed from this node —
    a local metadata read (reference LeaderElection.java:35-44), so an
    isolated node answers with its stale pre-partition view."""

    def inspect(self) -> Tuple[Optional[str], int]:
        def go():
            view = self.cluster.stale_views.get(self.node)
            return view if view is not None else (self.cluster.leader,
                                                  self.cluster.term)
        return self._do(go, local=True)

"""Systems under test.

The framework needs a pluggable SUT boundary shaped like the reference's
TestStateMachine.receive + sync client API (SURVEY.md §2.3 end). Two
implementations:

  * `inmemory` — a single-copy, in-process linearizable SUT with injectable
    latency, timeouts, pauses and kills. This is the "stub SUT" of the
    minimum end-to-end slice (SURVEY.md §7.3) and the fixture for harness
    tests (the reference's analogue is its docker localhost flow, §4).
  * `native/` (C++ raft server + TCP wire protocol, separate top-level
    dir) — the real distributed SUT, connected via the same connection
    interfaces.
"""

from .inmemory import InMemoryCluster  # noqa: F401

"""Multi-key history decomposition — batched across keys.

Equivalent of jepsen.independent/checker (reference register.clj:106):
ops whose values are ``(key, value)`` tuples are split into per-key
sub-histories, each checked independently.

TPU-first twist (reworked for the scenario tier): per-key checking is
not a loop over keys — the per-key sub-histories are exactly the batch
dimension the frontier kernel vmaps over (SURVEY.md §2.4 row 2), and
batch-axis rows never exchange state in any kernel family
(doc/checker-design.md §8 — the same independence argument graftd's
cross-request coalescing rests on). :func:`check_keyed` is the one home
of that path: every key's sub-history is ENCODED EXACTLY ONCE
(`history.packing.encode_history`), the encodings enter
`checker.linearizable.check_encoded` as ONE cross-key batch (dense
grouping, pow2+midpoint bucketing, macro compaction, the chunked
wavefront and the weaker-consistency rungs all apply unchanged), and
the per-key verdicts demux back by position — verdict-identical to K
sequential checker invocations, measured K× fewer launches.
`IndependentLinearizable` is the Checker-protocol face of it;
`IndependentChecker` remains the generic (non-frontier) composition.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from ..history.ops import History
from ..history.packing import encode_history
from ..models.base import Model
from .base import Checker, merge_valid
from .linearizable import DEFAULT_MAX_CPU_CONFIGS, check_encoded


def split_by_key(history: History) -> Dict:
    """Split a history of (key, value)-tupled ops into per-key histories,
    unwrapping values. Ops without tuple values raise — mixing independent
    and plain ops in one history is a bug."""
    subs: Dict = {}
    for op in history:
        if op.value is None and op.type == "invoke":
            raise ValueError(
                f"independent history contains untupled op: {op}"
            )
        key, value = op.value if op.value is not None else (None, None)
        if key is None:
            continue
        sub = subs.setdefault(key, History())
        sub.append(op.replace(value=value, index=op.index))
    return subs


def check_keyed(subs: Dict, model, algorithm: str = "auto",
                n_configs: Optional[int] = None,
                n_slots: Optional[int] = None,
                max_cpu_configs: Optional[int] = DEFAULT_MAX_CPU_CONFIGS,
                consistency: str = "linearizable") -> Dict:
    """Check per-key sub-histories as ONE cross-key `check_encoded`
    batch (module docstring). Returns {key: result dict} in the input's
    key order. This is the multi-key seam both the live checker and the
    service-tier admission ride: encode once, batch once, demux."""
    keys = list(subs.keys())
    if not keys:
        return {}
    encs = [encode_history(subs[k], model) for k in keys]
    rs = check_encoded(encs, model, algorithm, n_configs, n_slots,
                       max_cpu_configs=max_cpu_configs,
                       consistency=consistency)
    return dict(zip(keys, rs))


class IndependentChecker(Checker):
    """Generic per-key composition: run `checker_factory()` per key.
    (Kept for NON-frontier checkers; frontier models take the batched
    `IndependentLinearizable`/`check_keyed` path instead of K sequential
    invocations.)"""

    def __init__(self, checker_factory: Callable[[], Checker]):
        self.checker_factory = checker_factory

    def check(self, test, history, opts=None) -> dict:
        if not isinstance(history, History):
            history = History(history)
        subs = split_by_key(history.client_ops())
        results = {
            str(k): self.checker_factory().check(test, sub, opts)
            for k, sub in subs.items()
        }
        return {
            "valid?": merge_valid(r.get("valid?") for r in results.values()),
            "key-count": len(subs),
            "results": results,
        }


class IndependentLinearizable(Checker):
    """Per-key linearizability (or a weaker rung) as one batched kernel
    launch over the cross-key batch axis."""

    def __init__(self, model_factory: Callable[[], Model],
                 algorithm: str = "auto",
                 n_configs: Optional[int] = None,
                 n_slots: Optional[int] = None,
                 max_cpu_configs: Optional[int] = None,
                 consistency: str = "linearizable"):
        self.model_factory = model_factory
        self.algorithm = algorithm
        self.n_configs = n_configs
        self.n_slots = n_slots
        self.max_cpu_configs = max_cpu_configs or DEFAULT_MAX_CPU_CONFIGS
        self.consistency = consistency

    def check(self, test, history, opts=None) -> dict:
        from .base import INVALID
        from .counterexample import (attach_counterexample,
                                     write_counterexample_html)

        if not isinstance(history, History):
            history = History(history)
        subs = split_by_key(history.client_ops())
        if not subs:
            return {"valid?": True, "key-count": 0, "results": {}}
        model = self.model_factory()
        keyed = check_keyed(subs, model, self.algorithm, self.n_configs,
                            self.n_slots,
                            max_cpu_configs=self.max_cpu_configs,
                            consistency=self.consistency)
        store_dir = (test or {}).get("store_dir")
        for k, r in keyed.items():
            if r.get("valid?") is INVALID:
                attach_counterexample(r, subs[k], model,
                                      max_cpu_configs=self.max_cpu_configs,
                                      consistency=self.consistency)
                write_counterexample_html(r, subs[k], store_dir,
                                          f"counterexample-{k}.html")
        results = {str(k): r for k, r in keyed.items()}
        return {
            "valid?": merge_valid(r.get("valid?") for r in results.values()),
            "key-count": len(keyed),
            "results": results,
        }

"""Multi-key history decomposition.

Equivalent of jepsen.independent/checker (reference register.clj:106):
ops whose values are ``(key, value)`` tuples are split into per-key
sub-histories, each checked independently.

TPU-first twist: for linearizability this is not a loop over keys — the
per-key sub-histories are exactly the batch dimension the frontier kernel
vmaps over (SURVEY.md §2.4 row 2), so `IndependentLinearizable` packs all
keys into ONE batched kernel launch.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from ..history.ops import History
from ..models.base import Model
from .base import Checker, merge_valid
from .linearizable import check_histories


def split_by_key(history: History) -> Dict:
    """Split a history of (key, value)-tupled ops into per-key histories,
    unwrapping values. Ops without tuple values raise — mixing independent
    and plain ops in one history is a bug."""
    subs: Dict = {}
    for op in history:
        if op.value is None and op.type == "invoke":
            raise ValueError(
                f"independent history contains untupled op: {op}"
            )
        key, value = op.value if op.value is not None else (None, None)
        if key is None:
            continue
        sub = subs.setdefault(key, History())
        sub.append(op.replace(value=value, index=op.index))
    return subs


class IndependentChecker(Checker):
    """Generic per-key composition: run `checker_factory()` per key."""

    def __init__(self, checker_factory: Callable[[], Checker]):
        self.checker_factory = checker_factory

    def check(self, test, history, opts=None) -> dict:
        if not isinstance(history, History):
            history = History(history)
        subs = split_by_key(history.client_ops())
        results = {
            str(k): self.checker_factory().check(test, sub, opts)
            for k, sub in subs.items()
        }
        return {
            "valid?": merge_valid(r.get("valid?") for r in results.values()),
            "key-count": len(subs),
            "results": results,
        }


class IndependentLinearizable(Checker):
    """Per-key linearizability as one batched TPU kernel launch."""

    def __init__(self, model_factory: Callable[[], Model],
                 algorithm: str = "auto",
                 n_configs: Optional[int] = None,
                 n_slots: Optional[int] = None,
                 max_cpu_configs: Optional[int] = None):
        from .linearizable import DEFAULT_MAX_CPU_CONFIGS

        self.model_factory = model_factory
        self.algorithm = algorithm
        self.n_configs = n_configs
        self.n_slots = n_slots
        self.max_cpu_configs = max_cpu_configs or DEFAULT_MAX_CPU_CONFIGS

    def check(self, test, history, opts=None) -> dict:
        from .linearizable import INVALID
        from .counterexample import (attach_counterexample,
                                     write_counterexample_html)

        if not isinstance(history, History):
            history = History(history)
        subs = split_by_key(history.client_ops())
        if not subs:
            return {"valid?": True, "key-count": 0, "results": {}}
        keys = list(subs.keys())
        model = self.model_factory()
        rs = check_histories(
            [subs[k] for k in keys], model, self.algorithm,
            self.n_configs, self.n_slots,
            max_cpu_configs=self.max_cpu_configs,
        )
        store_dir = (test or {}).get("store_dir")
        for k, r in zip(keys, rs):
            if r.get("valid?") is INVALID:
                attach_counterexample(r, subs[k], model,
                                      max_cpu_configs=self.max_cpu_configs)
                write_counterexample_html(r, subs[k], store_dir,
                                          f"counterexample-{k}.html")
        results = {str(k): r for k, r in zip(keys, rs)}
        return {
            "valid?": merge_valid(r.get("valid?") for r in results.values()),
            "key-count": len(keys),
            "results": results,
        }

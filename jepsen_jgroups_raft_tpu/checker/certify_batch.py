"""Batched NumPy certifier core (ISSUE 15 tentpole (b)).

`consistency.certify_encoded` decides most rows at the lin rung and the
weak rungs, one row at a time, in pure Python — after PR 14 it IS the
production hot path, so its per-`model.step` interpreter overhead is
the fleet's wall clock. This module runs the certifier's GREEDY path
(flips == 0: direct commits, eager read-only commits, sweeps, and
first-choice candidate commits — everything short of a backtracking
RESTORE) vectorized across a whole batch of rows with columnar
`Model.step` twins (`Model.step_columnar`, numpy arrays over the batch
axis), falling back row-by-row to the scalar engine the moment a row
would need a restore (a dead end), hits anything the columnar pass
cannot faithfully mirror, or exceeds the shape caps.

Equivalence contract (doc/checker-design.md §17): for every row the
outcome triple is IDENTICAL to ``certify_encoded(enc, model,
max_steps=...)`` —

* a row the batch scan completes certified returns ``(True, "greedy",
  0)``, and the scalar engine would have returned exactly that: the
  scan mirrors the scalar commit rules step for step (eager read-only
  commits at OPEN, post-commit sweeps, direct FORCE commits, and the
  value-guided candidate ordering ``(enables, will-be-forced, open
  order)`` including the 1-step lookahead and the enable/observe
  bitmask short-circuit), and a scalar run that never restores a
  choice point never reads its stack — so the two paths traverse the
  same state sequence and count the same `model.step` calls;
* a row whose mirrored step count exceeds its abort budget returns
  ``(False, None, 0)`` — the scalar wrapper aborts at the same
  cumulative count (counts are monotone, so "exceeds anywhere" equals
  "exceeds at the same totals");
* every other row — a dead end (no legal candidate at an uncommitted
  FORCE), a malformed stream, shape caps — re-runs the SCALAR engine
  from scratch, which owns backtracking, flip budgets, and error
  behavior, so tiers ``backtrack``/undecided and every raise are
  byte-for-byte the scalar's.

``JGRAFT_CERTIFY_BATCH=0`` disables the batch pass entirely (the
ablation/differential arm: every row takes today's scalar engine);
``JGRAFT_CERTIFY_BATCH_MIN`` is the engagement floor (default 96 rows
— below it the numpy pass costs more than it saves; tests pin identity
by forcing 1). A measured per-bucket gate (the PR-14 idiom:
`certify_batch_min_hit` / `certify_batch_min_obs`) routes
backtrack-dominated buckets scalar-first once their observed
batch-decided fraction proves the scan is pure overhead there —
routing only, never verdicts. Every knob parses via
`platform.env_int`/`env_float` (garbage never crashes an importer).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..history.packing import (EV_FORCE, EV_OPEN, EncodedHistory,
                               bucket_rows)
from ..platform import env_float, env_int

#: Window cap for the slot tables: a row holding more concurrent slots
#: than this (a pathologically crash-polluted stream) routes scalar —
#: the per-slot sweep/candidate loops cost O(S) numpy passes per event.
_SLOT_CAP = 64

#: Default engagement floor (rows). The batch pass costs ~O(#FORCEs)
#: numpy call rounds regardless of B while the scalar engine costs
#: O(B·E) interpreter steps, so the crossover is a nearly E-independent
#: row count: measured on the 1-CPU host it sits at ~96-128 rows for
#: the happy-path families (queue at 200-op streams: 0.27x at B=16,
#: 1.17x at B=128, 1.73x at B=256). The floor therefore aims the core
#: at the bulk surfaces (bench batches, rung ladders, big submissions)
#: and keeps small graftd requests on the scalar engine they already
#: win.
_DEFAULT_MIN_ROWS = 96

# Row status codes for the scan.
_ACTIVE, _CERT, _FALLBACK, _ABORT = 0, 1, 2, 3

#: "Unbounded" abort-budget sentinel (rows with no max_steps).
_NO_BUDGET = np.int64(1) << 62

#: Composite candidate-ordering key: (enables, not-forced, open-order
#: id) packed into one int64 so argmin over the slot axis mirrors the
#: scalar sort on ``(0, enables, forced_rank, k)``.
_KEY_ENABLES = np.int64(1) << 40
_KEY_OPTIONAL = np.int64(1) << 39


def certify_batch_on() -> bool:
    """Whether the batched certifier core fronts the scalar engine.
    ``JGRAFT_CERTIFY_BATCH=0`` restores the row-by-row scalar loop
    everywhere (outcomes are identical either way — pinned by the
    differential tests; this is the A/B arm)."""
    return env_int("JGRAFT_CERTIFY_BATCH", 1, minimum=0) != 0


def certify_batch_min_rows() -> int:
    """Engagement floor (``JGRAFT_CERTIFY_BATCH_MIN``, default 96)."""
    return env_int("JGRAFT_CERTIFY_BATCH_MIN", _DEFAULT_MIN_ROWS,
                   minimum=1)


def certify_batch_min_hit() -> float:
    """Measured-gating floor (``JGRAFT_CERTIFY_BATCH_MIN_HIT``, default
    0.25): a bucket whose observed batch-decided fraction (rows the
    scan settled itself — certified or aborted — without a scalar
    re-run) sits below this routes scalar-first. The backtrack-
    dominated families (register at long streams: ~all rows need a
    restore) otherwise pay the full vectorized scan AND the scalar
    engine per row — measured 0.76x there, vs 2.0-2.5x on the
    happy-path families. Routing only, never verdicts — the same
    stance as `autotune.lin_fastpath_route`."""
    return env_float("JGRAFT_CERTIFY_BATCH_MIN_HIT", 0.25, minimum=0.0)


def certify_batch_min_obs() -> int:
    """Rows a bucket must be observed over before the hit-rate gate may
    route it scalar-first (``JGRAFT_CERTIFY_BATCH_MIN_OBS``, default
    64): trying IS measuring, so unknown buckets always try."""
    return env_int("JGRAFT_CERTIFY_BATCH_MIN_OBS", 64, minimum=1)


# Process-local measured gate, keyed like `autotune.lin_fastpath_sig`
# (family x pow2+midpoint event bucket — hit-rate is a property of the
# workload family; fragmenting by window would starve the gate). In-
# memory only, unlike the lin gate's fingerprint store: the batch core
# runs UNDER that gate, engages per call, and must stay deterministic
# under pytest where the autotune store is off.
_GATE_LOCK = threading.Lock()
_GATE: Dict[tuple, List[int]] = {}   # sig -> [rows_observed, hits]


def _gate_sig(model, enc: EncodedHistory) -> tuple:
    return (type(model).__name__, bucket_rows(max(enc.n_events, 1), 32))


def _gate_allows(sig: tuple) -> bool:
    with _GATE_LOCK:
        rows, hits = _GATE.get(sig, (0, 0))
    if rows < certify_batch_min_obs():
        return True
    return hits / rows >= certify_batch_min_hit()


def _gate_observe(sig: tuple, rows: int, hits: int) -> None:
    with _GATE_LOCK:
        rec = _GATE.setdefault(sig, [0, 0])
        rec[0] += rows
        rec[1] += hits


def reset_gate() -> None:
    """Forget every measured bucket (tests + the A/B harness: a cold
    gate re-observes from scratch)."""
    with _GATE_LOCK:
        _GATE.clear()


def certify_many(encs: Sequence[EncodedHistory], model,
                 max_steps=None, budget: Optional[int] = None
                 ) -> List[Tuple[bool, Optional[str], int]]:
    """Batch entry: per-row ``(certified, tier, flips)`` triples,
    outcome-identical to calling :func:`..consistency.certify_encoded`
    per row with the same per-row ``max_steps`` (scalar | sequence |
    None) and ``budget``. Routes eligible rows through the vectorized
    greedy scan and everything else — ineligible rows, fallback rows —
    through the scalar engine."""
    from .consistency import certify_encoded

    n = len(encs)
    if isinstance(max_steps, (list, tuple, np.ndarray)):
        ms_list = [None if m is None or m <= 0 else int(m)
                   for m in max_steps]
    else:
        ms_list = [None if max_steps is None or max_steps <= 0
                   else int(max_steps)] * n

    results: List = [None] * n
    batch_idx: List[int] = []
    if certify_batch_on() and getattr(model, "step_columnar", None):
        batch_idx = [i for i in range(n)
                     if encs[i].n_events > 0
                     and encs[i].n_slots <= _SLOT_CAP
                     and _gate_allows(_gate_sig(model, encs[i]))]
        if len(batch_idx) < certify_batch_min_rows():
            batch_idx = []
    if batch_idx:
        status = _batch_scan([encs[i] for i in batch_idx], model,
                             [ms_list[i] for i in batch_idx])
        obs: Dict[tuple, List[int]] = {}
        for j, i in enumerate(batch_idx):
            if status[j] == _CERT:
                results[i] = (True, "greedy", 0)
            elif status[j] == _ABORT:
                results[i] = (False, None, 0)
            # _FALLBACK rows re-run scalar below
            rec = obs.setdefault(_gate_sig(model, encs[i]), [0, 0])
            rec[0] += 1
            # an ABORT is a batch win too: the scan settled the row
            # (same abort the scalar wrapper reaches) without a
            # scalar re-run
            rec[1] += int(status[j] != _FALLBACK)
        for sig, (rows, hits) in obs.items():
            _gate_observe(sig, rows, hits)
    for i in range(n):
        if results[i] is None:
            results[i] = certify_encoded(encs[i], model, budget=budget,
                                         max_steps=ms_list[i])
    return results


def _decode_forced(events: np.ndarray) -> np.ndarray:
    """Per-OPEN-event will-this-op-ever-be-FORCEd flags, vectorized.

    Within one slot, opens and forces strictly alternate (a slot is
    recycled only by a FORCE; a crashed op holds its slot forever), so
    the i-th open in a slot is forced iff the slot sees at least i+1
    forces. Returns an int8 array over EVENTS (1 at forced OPEN rows)."""
    et = events[:, 0]
    slots = events[:, 1]
    out = np.zeros(len(events), dtype=np.int8)
    open_pos = np.flatnonzero(et == EV_OPEN)
    force_pos = np.flatnonzero(et == EV_FORCE)
    if not len(open_pos):
        return out
    n_slots = int(slots.max()) + 1 if len(slots) else 1
    force_count = np.bincount(slots[force_pos], minlength=n_slots)
    # rank of each open within its slot (opens are in stream order)
    oslots = slots[open_pos]
    order = np.argsort(oslots, kind="stable")
    sorted_slots = oslots[order]
    starts = np.searchsorted(sorted_slots, np.arange(n_slots), "left")
    rank_sorted = np.arange(len(open_pos)) - starts[sorted_slots]
    rank = np.empty(len(open_pos), dtype=np.int64)
    rank[order] = rank_sorted
    out[open_pos] = (rank < force_count[oslots]).astype(np.int8)
    return out


def _row_guide(model, events: np.ndarray, forced_at_open: np.ndarray):
    """Per-row value-guide masks aligned with OPEN events, via the
    scalar `_value_guide_masks` (one python pass per row; models
    without the enable/observe hooks answer None after one op, and a
    too-wide domain bails at ~63 distinct values, so the pass is cheap
    exactly where it is useless). Returns (ok, em_at_event, om_at_event)
    with int64 masks (zeros when the guide is off)."""
    from .consistency import _value_guide_masks

    open_pos = np.flatnonzero(events[:, 0] == EV_OPEN)
    ops = [tuple(r) for r in events[open_pos][:, 2:5].tolist()]
    forced = [bool(v) for v in forced_at_open[open_pos].tolist()]
    em_ev = np.zeros(len(events), dtype=np.int64)
    om_ev = np.zeros(len(events), dtype=np.int64)
    guide = _value_guide_masks(model, ops, forced)
    if guide is None:
        return False, em_ev, om_ev
    em_ev[open_pos] = guide[0]
    om_ev[open_pos] = guide[1]
    return True, em_ev, om_ev


def _batch_scan(encs: Sequence[EncodedHistory], model,
                ms_list: Sequence[Optional[int]]) -> np.ndarray:
    """The vectorized greedy scan. Returns per-row status codes
    (_CERT / _FALLBACK / _ABORT). See the module docstring for the
    equivalence argument; the step-count bookkeeping deliberately
    mirrors the scalar engine's wrapper call for call.

    Iteration shape: the scalar engine's state only changes at commits
    (mutator FORCEs and candidate commits) — between two FORCEs every
    event is an OPEN (or PAD), eager read-only probes all evaluate at
    the SAME state, and slots within one open run are distinct (a slot
    is recycled only by a FORCE; `macro_compact` leans on the same
    fact) — so each outer iteration advances every active row through
    its ENTIRE current open run as one ragged gather/scatter plus one
    columnar step call, then handles one FORCE (or one candidate
    commit at it). The trip count is therefore ~#FORCEs + #candidate
    commits, not #events, which is what makes the numpy pass win on
    the host."""
    B = len(encs)
    E = max(e.n_events for e in encs)
    S = max(max(e.n_slots for e in encs), 1)
    step = model.step_columnar
    # read-only opcode lookup table (encoder-produced fcodes are dense
    # and bounded by the model's n_fcodes; clip guards hand-built junk)
    n_f = int(getattr(model, "n_fcodes", 0) or 0) + 1
    ro_lut = np.zeros(n_f + 1, dtype=bool)
    for fc in (getattr(model, "readonly_fcodes", ()) or ()):
        if 0 <= int(fc) < n_f:
            ro_lut[int(fc)] = True
    slot_col = np.arange(S, dtype=np.int64)[None, :]

    ev = np.zeros((B, E, 5), dtype=np.int32)
    n_ev = np.zeros(B, dtype=np.int64)
    forced_at = np.zeros((B, E), dtype=np.int8)
    em_at = np.zeros((B, E), dtype=np.int64)
    om_at = np.zeros((B, E), dtype=np.int64)
    gok = np.zeros(B, dtype=bool)
    # next FORCE position at-or-after each event position (per row)
    nf_at = np.zeros((B, E + 1), dtype=np.int64)
    n_ops_total = 0
    for i, e in enumerate(encs):
        ne = e.n_events
        ev[i, :ne] = e.events
        n_ev[i] = ne
        fa = _decode_forced(e.events)
        forced_at[i, :ne] = fa
        gok[i], em_at[i, :ne], om_at[i, :ne] = \
            _row_guide(model, e.events, fa)
        fpos = np.flatnonzero(e.events[:, 0] == EV_FORCE)
        nxt = np.searchsorted(fpos, np.arange(ne + 1), side="left")
        nf_at[i, :ne + 1] = np.where(nxt < len(fpos),
                                     fpos[np.minimum(nxt, len(fpos) - 1)]
                                     if len(fpos) else ne, ne)
        nf_at[i, ne + 1:] = ne
        n_ops_total = max(n_ops_total, e.n_ops)

    ms = np.full(B, _NO_BUDGET, dtype=np.int64)
    for i, m in enumerate(ms_list):
        if m is not None:
            ms[i] = m
    # with no abort budget anywhere, the mirrored step accounting can
    # be skipped wholesale (the weak-rung apply_rung path)
    any_budget = bool((ms < _NO_BUDGET).any())

    rows = np.arange(B)
    pos = np.zeros(B, dtype=np.int64)
    state = np.full(B, np.int32(model.init_state()), dtype=np.int32)
    count = np.zeros(B, dtype=np.int64)
    status = np.zeros(B, dtype=np.int8)
    kcnt = np.zeros(B, dtype=np.int64)

    occ = np.zeros((B, S), dtype=bool)       # slot holds a live op
    sdone = np.zeros((B, S), dtype=bool)     # that op already committed
    sro = np.zeros((B, S), dtype=bool)       # read-only opcode
    sforced = np.zeros((B, S), dtype=bool)   # op will see a FORCE
    sf = np.zeros((B, S), dtype=np.int32)
    sa = np.zeros((B, S), dtype=np.int32)
    sb = np.zeros((B, S), dtype=np.int32)
    sk = np.zeros((B, S), dtype=np.int64)    # open-order op id
    sem = np.zeros((B, S), dtype=np.int64)
    som = np.zeros((B, S), dtype=np.int64)

    def abort_check(m):
        a = m & (count > ms)
        status[a] = _ABORT
        return m & ~a

    def sweep(m):
        """Post-commit eager pass: every occupied, read-only,
        uncommitted slot gets one legality probe at the (already
        advanced) state — exactly the scalar `sweep`, evaluated as ONE
        2D-broadcast columnar step over the whole slot table (read-only
        commits are state-preserving and each pending op is probed once
        at the same state, so the committed set and the step count are
        probe-order-independent; counts are monotone, so adding a
        sweep's probes in bulk reaches the same abort decision as the
        scalar's one-at-a-time wrapper)."""
        mm = (occ & sro & ~sdone) & m[:, None]
        if not mm.any():
            return
        if any_budget:
            count[:] += mm.sum(axis=1)
            abort_check(m)
        _, lg = step(state[:, None], sf, sa, sb)
        sdone[:] |= mm & lg

    # Each iteration lands every active row on its next FORCE (or the
    # end) and resolves one commit there, so the trip count is bounded
    # by #FORCEs + #candidate commits + 1 ≤ E + ops; anything past
    # that is a malformed stream — routed scalar, where the error
    # behavior is authoritative.
    max_iter = E + n_ops_total + 4
    for _ in range(max_iter):
        act = status == _ACTIVE
        if not act.any():
            break
        if int((status == _FALLBACK).sum()) > B // 2:
            # dead-end-dominated batch (the backtrack-heavy families):
            # most rows are headed for the scalar engine anyway, so
            # stop paying the vectorized scan for the rest — FALLBACK
            # is always outcome-preserving, only slower
            status[act] = _FALLBACK
            break

        # ---- bulk open-run phase: advance every active row to its
        # next FORCE, storing/eager-probing the run's opens ----------
        run_to = nf_at[rows, np.minimum(pos, E)]
        m_run = act & (run_to > pos)
        if m_run.any():
            r = np.flatnonzero(m_run)
            lens = (run_to - pos)[r]
            tot = int(lens.sum())
            row_rep = np.repeat(r, lens)
            cum = np.cumsum(lens)
            idx = (np.arange(tot) - np.repeat(cum - lens, lens)
                   + np.repeat(pos[r], lens))
            rowev = ev[row_rep, idx]
            is_open = rowev[:, 0] == EV_OPEN
            f0, a0, b0 = rowev[:, 2], rowev[:, 3], rowev[:, 4]
            sl0 = rowev[:, 1]
            badm = is_open & (sl0 >= S)
            if badm.any():
                status[row_rep[badm]] = _FALLBACK
            is_ro = is_open & ro_lut[np.minimum(f0, n_f)]
            # eager probes: one columnar step at the run's (constant)
            # state for every read-only open in it; counts first (the
            # scalar wrapper aborts before using a result, and counts
            # are monotone so "exceeds mid-run" ≡ "exceeds on the
            # run's total")
            if any_budget:
                count[:] += np.bincount(row_rep[is_ro], minlength=B)
                abort_check(m_run)
            eager = np.zeros(tot, dtype=bool)
            if is_ro.any():
                _, lg = step(state[row_rep], f0, a0, b0)
                eager = is_ro & lg
            live = (status[row_rep] == _ACTIVE) & is_open
            rr, ss = row_rep[live], np.minimum(sl0[live], S - 1)
            # slots within one run are distinct per row, so this
            # scatter never collides on (row, slot)
            occ[rr, ss] = True
            sdone[rr, ss] = eager[live]
            sro[rr, ss] = is_ro[live]
            sforced[rr, ss] = forced_at[row_rep, idx][live] != 0
            sf[rr, ss] = f0[live]
            sa[rr, ss] = a0[live]
            sb[rr, ss] = b0[live]
            opens_before = np.cumsum(is_open) - is_open
            run_open_rank = (opens_before
                             - np.repeat(opens_before[cum - lens], lens))
            sk[rr, ss] = (kcnt[row_rep] + run_open_rank)[live]
            sem[rr, ss] = em_at[row_rep, idx][live]
            som[rr, ss] = om_at[row_rep, idx][live]
            kcnt[r] += np.bincount(row_rep[is_open],
                                   minlength=B)[r]
            adv = np.flatnonzero(m_run & (status == _ACTIVE))
            pos[adv] = run_to[adv]

        act = status == _ACTIVE
        done_rows = act & (pos >= n_ev)
        status[done_rows] = _CERT
        m_force = act & ~done_rows
        if not m_force.any():
            continue

        # ---- FORCE phase: skip committed ops, direct-commit legal
        # ones (+sweep), or run one candidate commit ------------------
        cur = ev[rows, np.minimum(pos, E - 1)]
        sl = np.minimum(cur[:, 1], S - 1)
        bad_slot = m_force & (cur[:, 1] >= S)
        status[bad_slot] = _FALLBACK
        m_force = m_force & ~bad_slot

        fdone = sdone[rows, sl]
        mskip = m_force & fdone
        r = np.flatnonzero(mskip)
        occ[r, sl[r]] = False
        pos[r] += 1
        mchk = m_force & ~fdone
        # a FORCE must close a live op; anything else is malformed —
        # scalar raises, so hand the row to it
        bad = mchk & ~occ[rows, sl]
        status[bad] = _FALLBACK
        mchk = mchk & ~bad
        if mchk.any():
            tf = sf[rows, sl]
            ta = sa[rows, sl]
            tb = sb[rows, sl]
            if any_budget:
                count[mchk] += 1
                mchk = abort_check(mchk)
            ns, lg = step(state, tf, ta, tb)
            mlegal = mchk & lg
            # direct greedy commit: the scalar re-steps for the commit
            # (+1) then sweeps
            if any_budget:
                count[mlegal] += 1
                mlegal = abort_check(mlegal)
            r = np.flatnonzero(mlegal)
            if len(r):
                state[r] = ns[r]
                sdone[r, sl[r]] = True
                sweep(mlegal)
                mlegal = mlegal & (status == _ACTIVE)
                r = np.flatnonzero(mlegal)
                occ[r, sl[r]] = False
                pos[r] += 1
            mcand = mchk & ~lg & (status == _ACTIVE)
            if mcand.any():
                # ---- one vectorized candidate commit (the scalar
                # candidates() + first-choice commit) per stalled row;
                # the row stays at the FORCE and re-probes it next
                # iteration ----------------------------------------
                # candidates() re-probes the forced op first (+1)
                if any_budget:
                    count[mcand] += 1
                    mcand = abort_check(mcand)
                cm = (occ & ~sdone & mcand[:, None]
                      & (slot_col != sl[:, None]))
                if any_budget:
                    count[:] += cm.sum(axis=1)
                    mcand = abort_check(mcand)
                ns2, lg2 = step(state[:, None], sf, sa, sb)
                cl = cm & lg2
                # guide short-circuit: a mask proving the candidate
                # exposes nothing the forced op observes skips the
                # 1-step lookahead (enables stays 1)
                om_e = som[rows, sl][:, None]
                need = cl & (~gok[:, None] | ((sem & om_e) != 0))
                if any_budget:
                    count[:] += need.sum(axis=1)
                    mcand = abort_check(mcand)
                _, lg3 = step(ns2, tf[:, None], ta[:, None],
                              tb[:, None])
                enables = np.where(need & lg3, np.int64(0),
                                   np.int64(1))
                key = (enables * _KEY_ENABLES
                       + np.where(sforced, np.int64(0),
                                  np.int64(1)) * _KEY_OPTIONAL
                       + sk)
                key = np.where(cl, key, _NO_BUDGET)
                best = key.argmin(axis=1)
                picked = key[rows, best] < _NO_BUDGET
                # dead end: the scalar engine would start restoring
                # choice points — its territory
                status[mcand & ~picked] = _FALLBACK
                mpick = mcand & picked
                # the scalar main loop re-steps the choice to commit
                if any_budget:
                    count[mpick] += 1
                    mpick = abort_check(mpick)
                r = np.flatnonzero(mpick)
                if len(r):
                    state[r] = ns2[r, best[r]]
                    sdone[r, best[r]] = True
                    sweep(mpick)
    status[status == _ACTIVE] = _FALLBACK  # trip-count bound: malformed
    return status

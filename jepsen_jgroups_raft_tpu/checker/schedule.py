"""Chunked wavefront scheduler: decided-row eviction + pipelined dispatch.

The ISSUE-3 tentpole. The monolithic execution path pays one padded-E
`lax.scan` per window group, strictly serialized across groups: every
row rides the full scan even after its verdict is certain (frontier died
→ invalid) or its real events are exhausted (the remaining schedule is
EV_PAD no-ops), and group k+1's kernel queues behind group k's on one
device. That is the classic finished-sequences-in-the-batch inefficiency
of batched inference; the fix here is the same shape as iteration-level
(continuous) batching in serving stacks (PAPERS.md: Orca/vLLM):

  * **Chunking** — the event scan advances in `chunk`-event units
    (`JGRAFT_SCAN_CHUNK`, 0 = legacy monolithic scan) through the
    chunked kernels of ops/dense_scan.py / ops/linear_scan.py, whose
    carry returns per-row `decided` / `exhausted` flags alongside the
    frontier. Sync-free spans coalesce: no row can exhaust before
    min(alive `n_events`) — host data — so the scheduler launches one
    kernel up to the next possible-retirement boundary instead of one
    per chunk (`_span_chunks`; per-launch overhead otherwise eats the
    eviction win).
  * **Eviction** — between chunks the flags come back to the host, the
    verdicts of finished rows are recorded, and survivors are
    recompacted to a smaller row bucket (`history.packing.bucket_rows`,
    the same pow2+midpoint series `pad_batch_bucketed` uses — so
    recompaction hits jit-cache entries the initial padding already
    compiled instead of triggering fresh XLA compiles).
  * **Early exit** — a group stops the moment all rows are decided.
    The chunk schedule covers the group's *bucketed* event length (what
    the legacy monolithic kernel scans), so skipping trailing pad
    chunks is a genuine saving over the monolithic reference, and is
    what `early_exit` reports.
  * **Pipelining** — each round dispatches every live group's next
    chunk before blocking on any result (JAX async dispatch), each
    chunk row-sharded over the device mesh
    (`parallel.mesh.chunk_sharding`), so one group's chunk executes
    mesh-wide exactly like the legacy `shard_map` path while the other
    groups' chunks queue behind it on every device — the host blocking
    on one group's flags never idles the ring.

Soundness (the checker/linearizable.py contract, unchanged): eviction
only ever removes rows whose verdict is already certain. A `decided`
row's (ok, overflow) pair is frozen — `ok` is monotone and flips False
exactly when the frontier empties, after which every event is a no-op on
the dead frontier — and an `exhausted` row only has EV_PAD no-ops left.
The scheduler maps the final pairs exactly as the monolithic caller
does (ok → valid; ~ok & ~overflow → invalid; ~ok & overflow → escalate),
so the chunked path can never report a verdict the monolithic scan would
not have (pinned bitwise by tests/test_chunked_scan.py differentials).
"""

from __future__ import annotations

import contextlib
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional

import numpy as np

from ..history.packing import bucket_rows
from ..platform import env_int

#: Default events per chunk. Calibrated on the north-star host-CPU bench
#: shape (1000×1k register, window groups W=5..8, real event counts
#: ~1472..1645 padded to a 2048 bucket): the win is bounded by how soon
#: after its last real event a row is evicted, so finer chunks help
#: until per-launch overhead bites — the calibration grid (legacy
#: ≈ c256 ≈ 12.2 s, c128 11.6 s, c64 11.5 s on a 256-row scale model;
#: per-launch overhead stays negligible down to 64) picks 128: most
#: north-star rows retire at the 1536 boundary (chunk 12/16) and the
#: rest one chunk later, vs chunk 7/8 for 256. JGRAFT_SCAN_CHUNK
#: overrides (0 = legacy monolithic scan — the ablation hook and
#: reference implementation).
DEFAULT_SCAN_CHUNK = 128


def scan_chunk() -> int:
    """Resolved chunk size: 0 disables chunking (legacy monolithic
    scan). Parsed defensively — a non-integer env value warns and uses
    the default instead of crashing the importer."""
    return env_int("JGRAFT_SCAN_CHUNK", DEFAULT_SCAN_CHUNK, minimum=0)


# ------------------------------------------------------------------ stats
# Aggregated across every wavefront this process runs (thread-safe: race
# mode drives the jax pass from a worker thread). bench.py consumes them
# per rep; checker/perf.py reads the innermost `stats_scope` so stored
# per-run artifacts never accumulate across checker invocations.

_STATS_LOCK = threading.Lock()
_STATS_ZERO = {"chunks_run": 0, "evicted_rows": 0, "groups_run": 0,
               "groups_early_exited": 0, "pipeline_overlap_s": 0.0,
               # cycle-tier counters (ISSUE 19): rows that skipped the
               # exact tier for size (the previously-silent cap skip),
               # graph nodes before/after SCC condensation, non-trivial
               # SCCs hit, and blocked-closure tile programs run.
               "cycle_size_skips": 0, "cycle_nodes_pre": 0,
               "cycle_nodes_post": 0, "cycle_scc_hits": 0,
               "cycle_tiles_run": 0}
_STATS = dict(_STATS_ZERO)
#: (scope dict, owner thread id) pairs; guarded by _STATS_LOCK,
#: innermost last. The owner id makes attribution THREAD-AFFINE under
#: concurrent scopes (graftd's multi-worker shards, ISSUE 7): counters
#: recorded by a thread that owns scopes land ONLY in that thread's
#: scopes — two shard executors checking concurrently no longer sum
#: each other's counters into both batches' stats. Counters from a
#: thread owning NO scope (race mode's engine threads) keep the old
#: every-active-scope behavior, so single-worker attribution is
#: unchanged bit for bit.
_SCOPES: List[tuple] = []


def _add_stats(**kw) -> None:
    tid = threading.get_ident()
    with _STATS_LOCK:
        owned = [s for s, o in _SCOPES if o == tid]
        targets = owned if owned else [s for s, _ in _SCOPES]
        for k, v in kw.items():
            _STATS[k] += v
            for scope in targets:
                scope[k] += v


def note_cycle(**kw) -> None:
    """Record cycle-tier counters (ISSUE 19) into the active scopes +
    process totals — ``cycle_size_skips`` is the previously-invisible
    cap skip (satellite: a row too big for the exact tier now leaves a
    trace in every stats surface), the rest feed the bench rung rows'
    ``cycle_*`` fields. Unknown keys are a programming error, caught
    loudly here rather than silently minted as new counters."""
    for k in kw:
        if k not in _STATS_ZERO:
            raise KeyError(f"unknown cycle counter {k!r}")
    _add_stats(**kw)


@contextlib.contextmanager
def stats_scope(label: Optional[str] = None):
    """Explicit per-run counter scope (ISSUE-4 satellite): counters
    accumulated while the scope is active land in the yielded dict too,
    isolated from everything before it. `core/runner.run_test` wraps
    each test's checking phase in one, so a process running
    back-to-back checks (soaks, `bench.py --suite` in one interpreter)
    stores per-run counters instead of process-lifetime accumulation.
    Nesting-safe (scopes stack) and thread-safe; the process-wide
    totals that `consume_stats` serves (the bench's per-rep read) are
    untouched.

    `label` threads a caller identity through the scope (ISSUE-5: the
    checking service labels each coalesced launch with the request ids
    riding it — "graftd:req-a,req-b" — so per-request trace records can
    attribute their shared launch's counters). The label is carried in
    the yielded dict under the non-counter key ``"label"``; `_add_stats`
    only ever touches counter keys, so it is never accumulated into."""
    scope = dict(_STATS_ZERO)
    if label is not None:
        scope["label"] = label
    with _STATS_LOCK:
        _SCOPES.append((scope, threading.get_ident()))
    try:
        yield scope
    finally:
        with _STATS_LOCK:
            # Remove by IDENTITY: list.remove compares by equality, and
            # two scopes with identical counters (e.g. nested, both
            # still zero) are equal dicts — remove() would pop the
            # outer one and crash the outer exit.
            for i, (s, _) in enumerate(_SCOPES):
                if s is scope:
                    del _SCOPES[i]
                    break


def snapshot_stats(scoped: bool = False) -> dict:
    """Copy of the accumulated chunked-scan counters (non-destructive).
    `scoped=True` returns the innermost active `stats_scope`'s counters
    — this run's work only; with concurrent scopes (multi-worker
    graftd) the innermost scope OWNED BY THIS THREAD wins — falling
    back to the process totals when no scope is active (direct
    `check_histories` callers outside a test run)."""
    with _STATS_LOCK:
        if scoped and _SCOPES:
            tid = threading.get_ident()
            for s, o in reversed(_SCOPES):
                if o == tid:
                    return dict(s)
            return dict(_SCOPES[-1][0])
        return dict(_STATS)


def consume_stats() -> dict:
    """Return and reset the accumulated process-wide counters (bench.py
    reads one timed rep's worth at a time). Active scopes are not
    reset — they already hold only their own span's counters."""
    global _STATS
    with _STATS_LOCK:
        out = dict(_STATS)
        _STATS = dict(_STATS_ZERO)
        return out


# ------------------------------------------------------ tier attribution
# ISSUE 13: every verdict records which tier of the decision ladder
# decided it (greedy / backtrack / cycle / mask / dense / sort / host /
# trivial). At fleet scale the cheap-tier hit-rate IS the capacity
# model, so the per-tier decided counts and wall time are first-class
# counters next to the chunked-scan stats: same process-wide totals +
# thread-affine scope attribution, surfaced by checker/perf.py,
# bench.py rows, and graftd's per-request stats.

_TIERS: dict = {}  # tier -> [rows, wall_s]; guarded by _STATS_LOCK


def note_tier(tier: str, rows: int = 1, wall_s: float = 0.0) -> None:
    """Record `rows` verdicts decided by `tier` (and the wall seconds
    attributed to them). Scope targeting mirrors `_add_stats`: a thread
    owning scopes feeds only its own (each scope's tier dict lives
    under the non-counter key ``"tiers"``)."""
    tid = threading.get_ident()
    with _STATS_LOCK:
        t = _TIERS.setdefault(tier, [0, 0.0])
        t[0] += rows
        t[1] += wall_s
        owned = [s for s, o in _SCOPES if o == tid]
        targets = owned if owned else [s for s, _ in _SCOPES]
        for scope in targets:
            e = scope.setdefault("tiers", {}).setdefault(tier, [0, 0.0])
            e[0] += rows
            e[1] += wall_s


def _format_tiers(raw: dict) -> dict:
    return {k: {"rows": v[0], "wall_s": v[1]} for k, v in raw.items()}


def snapshot_tiers(scoped: bool = False) -> dict:
    """Copy of the per-tier decided counters, ``{tier: {"rows",
    "wall_s"}}`` (non-destructive). `scoped=True` reads the innermost
    scope owned by this thread, like `snapshot_stats`."""
    with _STATS_LOCK:
        if scoped and _SCOPES:
            tid = threading.get_ident()
            for s, o in reversed(_SCOPES):
                if o == tid:
                    return _format_tiers(s.get("tiers", {}))
            return _format_tiers(_SCOPES[-1][0].get("tiers", {}))
        return _format_tiers(_TIERS)


def consume_tiers() -> dict:
    """Return and reset the process-wide per-tier counters (bench.py
    reads one timed window's worth at a time); active scopes keep
    their own accumulations, like `consume_stats`."""
    global _TIERS
    with _STATS_LOCK:
        out = _format_tiers(_TIERS)
        _TIERS = {}
        return out


# ------------------------------------------------------------- wavefront


@dataclass
class ChunkLaunch:
    """One window group queued for chunked execution.

    events: [B, E, 5] packed group batch (host numpy; pack_batch layout).
    n_events: [B] real event count per row (EncodedHistory.n_events).
    init_fn/step_fn: the chunked kernel pair from
        ops.dense_scan.make_dense_chunk_checker or
        ops.linear_scan.make_sort_chunk_checker.
    val_of: [B, S] per-history domain table (dense kernels) or None
        (sort kernel — its init_fn takes only n_events).
    e_sched: event length the chunk schedule must cover — the BUCKETED
        length the legacy monolithic kernel would scan (so early exit
        measures real savings vs the reference path); defaults to E.
    device: placement for this group's carry + chunk slices — a jax
        Device (host-routed groups), a batch-axis Sharding
        (`parallel.mesh.chunk_sharding`: rows spread over the mesh,
        row buckets padded to a multiple of the shard count), or None
        for default single-device placement.
    tag: kernel label for result/bench reporting.
    """

    events: np.ndarray
    n_events: np.ndarray
    init_fn: Callable
    step_fn: Callable
    val_of: Optional[np.ndarray] = None
    e_sched: Optional[int] = None
    device: Optional[object] = None
    tag: str = "dense-chunk"
    #: LONG merged clusters keep exact row counts (the legacy path pads
    #: floor_b=len(sub) for them: extra rows are pure width work on a
    #: depth-bound launch) and skip recompaction — their row counts are
    #: tiny, so eviction's value there is the early exit, not bucket
    #: shrinking, and per-eviction exact shapes would recompile.
    exact_rows: bool = False
    #: Per-launch chunk override (checker/autotune.py plans): None
    #: inherits the run-wide chunk (JGRAFT_SCAN_CHUNK), a positive
    #: value pins THIS launch's chunk — a whole-schedule value makes
    #: the launch effectively monolithic (one span, one flag sync)
    #: while staying on the wavefront driver.
    chunk: Optional[int] = None


@dataclass
class GroupOutcome:
    """Per-group result of a wavefront run; ok/overflow are [B_real].
    `chunks_run` counts LAUNCHES — sync-free spans are coalesced into
    one launch each (`_span_chunks`), so it is ≤ the chunk-unit count
    the schedule covers."""

    ok: np.ndarray
    overflow: np.ndarray
    wall_s: float
    chunks_run: int
    evicted_rows: int
    early_exit: bool
    tag: str = ""


@dataclass
class _GroupState:
    launch: ChunkLaunch
    padded_events: np.ndarray          # [B_real, E_pad, 5]
    chunk: int                         # this group's resolved chunk size
    scheduled: int                     # chunks the monolithic path implies
    slot_rows: np.ndarray              # [padded_B] original row id or -1
    carry: object                      # device pytree
    ok: np.ndarray                     # [B_real] final verdicts
    overflow: np.ndarray
    recorded: np.ndarray               # [B_real] bool
    cursor: int = 0                    # chunk-units already scanned
    launches_run: int = 0              # coalesced launches dispatched
    evicted: int = 0
    done: bool = False
    early_exit: bool = False
    t_start: float = 0.0
    wall_s: float = 0.0
    pending: Optional[tuple] = None
    intervals: List[tuple] = field(default_factory=list)  # in-flight spans


def build_dense_launches(model, groups, host_route=None):
    """Build the wavefront launch list for dense window groups — the
    one home of the placement policy (checker/_jax_pass and
    bench.run_chunks both route through it).

    groups: iterable of (rows, plan, batch) or (rows, plan, batch,
    tuned) — `rows` the caller's row ids, `plan` a DensePlan, `batch`
    the group's pack_batch OR pack_macro_batch dict (a "macro_p" key
    routes the group through the macro-event chunk kernels; `n_events`
    then counts macro rows, which is exactly what the span/exhaustion
    math must run on), `tuned` an optional checker/autotune.py
    TunedPlan applying this group's measured {scan_chunk, mesh_fanout}
    (the macro payload half of a plan acts earlier, at pack time —
    autotune.pack_group). The launch order is policy and lives HERE:
    largest group first, so big groups' chunks queue ahead of small
    ones on every device (callers must not pre-sort — the bench and
    the checker must measure the same schedule).
    host_route(n_rows_bucketed, e_len) -> bool optionally routes a
    whole group to the host cpu device (the PLATFORM_ROUTE_MIN_CELLS
    gate). Returns (launches, subs): subs[k] holds the row ids behind
    launches[k], in row order.

    Groups stay WHOLE and each chunk's kernel is an explicit
    `shard_map` over the batch axis of the device mesh
    (`parallel.mesh.chunk_sharding`; the wrap lives in
    ops/dense_scan._shard_chunk_fns): every device scans its row shard
    — the exact execution shape of the legacy `shard_map` path, whose
    row-parallelism is the measured win on every backend (2-core
    north-star A/B: mesh-sharded 116 s vs 250 s single-device
    monolithic). Two cheaper-looking alternatives lost: Python-level
    per-device group *slicing* reached only ~1.4–1.6× overlap with
    round-robin collect bubbles, and relying on jit's GSPMD sharding
    propagation kept the carry *placed* sharded but compiled a ~3×
    slower per-chunk program than the explicit wrap. Cross-group
    pipelining comes free: all live groups' chunks queue on every
    device, so the host blocking on one group's flags never idles the
    ring. LONG merged clusters (exact_rows) keep exact row counts on
    the default device — depth-bound few-row launches, sharding buys
    nothing — and host-routed groups pin whole to the host cpu
    device."""
    from ..ops.dense_scan import MERGE_MAX_EVENTS, make_dense_chunk_checker
    from ..parallel.mesh import chunk_sharding

    sharding = chunk_sharding()
    launches: list = []
    subs: list = []
    for grp in sorted(groups, key=lambda g: -len(g[0])):
        rows, plan, batch = grp[:3]
        tuned = grp[3] if len(grp) > 3 else None
        e_len = batch["events"].shape[1]
        # Both the LONG-group exact-padding policy and the host/TPU
        # cell gate were calibrated on LEGACY event counts; a macro
        # batch's ~2× shorter row count must not silently halve their
        # thresholds (a merged long cluster losing its depth-bound
        # exemption would host-route onto the placement measured 2.2×
        # slower). The scan schedule itself runs on macro rows.
        e_legacy = batch.get("legacy_events", e_len)
        exact = e_legacy > MERGE_MAX_EVENTS
        e_sched = e_len if exact else bucket_rows(e_len, 32)
        tag = plan.kernel_tag
        # Gate on the same PADDED shapes the legacy path feeds
        # _route_group_to_host (pad_batch_bucketed's row bucket and
        # floor_e=32 event bucket): an unbucketed length would flip
        # routing for groups near the PLATFORM_ROUTE_MIN_CELLS boundary.
        host = bool(host_route
                    and host_route(bucket_rows(len(rows)),
                                   e_legacy if exact
                                   else bucket_rows(e_legacy, 32)))
        if host:
            import jax

            tag += "@host"
            # Local cpu device: in a multi-process runtime
            # jax.devices("cpu") lists every host's cpu devices and
            # [0] may be a non-addressable remote one.
            placement = jax.local_devices(backend="cpu")[0]
        elif exact:
            placement = None
        elif tuned is not None and tuned.mesh_fanout > 0:
            # Per-group fan-out from the measured plan; the env knob
            # (JGRAFT_GROUP_DEVICES) stays the outer bound inside
            # chunk_sharding.
            placement = chunk_sharding(tuned.mesh_fanout)
        else:
            placement = sharding
        # A tuned scan_chunk pins this launch's chunk; 0 means "one
        # whole-schedule span" — effectively the monolithic reference
        # launch, still on the wavefront driver (same verdict path).
        chunk_override = None
        if tuned is not None and not exact:
            chunk_override = tuned.scan_chunk or max(e_sched, 1)
        init_fn, step_fn = make_dense_chunk_checker(
            model, plan.kind, plan.n_slots, plan.n_states,
            mesh=getattr(placement, "mesh", None),
            macro_p=batch.get("macro_p"))
        launches.append(ChunkLaunch(
            events=batch["events"], n_events=batch["n_events"],
            init_fn=init_fn, step_fn=step_fn, val_of=plan.val_of,
            e_sched=e_sched, device=placement, tag=tag,
            exact_rows=exact, chunk=chunk_override))
        subs.append(list(rows))
    return launches, subs


def _n_shards(placement) -> int:
    """Shard count of a launch placement: mesh size for a batch-axis
    Sharding, 1 for a concrete device or default placement."""
    mesh = getattr(placement, "mesh", None)
    return int(mesh.size) if mesh is not None else 1


def _bucket_launch_rows(launch: ChunkLaunch, n: int) -> int:
    """Row bucket for a launch's active set: the pow2+midpoint series,
    padded up to a multiple of the placement's shard count so a
    sharded launch always splits evenly over the mesh (the same
    rounding `pad_batch_bucketed(multiple_b=mesh)` applies on the
    legacy sharded path)."""
    b = bucket_rows(n)
    s = _n_shards(launch.device)
    return -(-b // s) * s


def _pad_idx(positions: List[int], bucket: int) -> np.ndarray:
    """Gather index padded to the bucket by repeating the first entry
    (pad slots are masked out of every flag read via slot_rows == -1)."""
    idx = np.asarray(positions + [positions[0]] * (bucket - len(positions)),
                     dtype=np.int32)
    return idx


def _init_group(launch: ChunkLaunch, chunk: int) -> _GroupState:
    import jax

    chunk = launch.chunk or chunk  # per-launch autotune override
    B, E = launch.events.shape[0], launch.events.shape[1]
    e_sched = max(launch.e_sched or E, E, 1)
    e_pad = ((e_sched + chunk - 1) // chunk) * chunk
    padded = launch.events
    if e_pad != E:
        # Row width follows the stream format: 5 legacy fields or
        # 3 + 4·P macro lanes (history/packing.py macro_compact).
        padded = np.zeros((B, e_pad, launch.events.shape[2]),
                          dtype=launch.events.dtype)
        padded[:, :E] = launch.events
    padded_b = B if launch.exact_rows else _bucket_launch_rows(launch, B)
    slot_rows = np.full((padded_b,), -1, dtype=np.int32)
    slot_rows[:B] = np.arange(B, dtype=np.int32)

    ne = np.zeros((padded_b,), dtype=np.int32)
    ne[:B] = launch.n_events
    put = (lambda x: jax.device_put(x, launch.device)) \
        if launch.device is not None else (lambda x: x)
    if launch.val_of is not None:
        vo = np.empty((padded_b,) + launch.val_of.shape[1:],
                      dtype=launch.val_of.dtype)
        vo[:B] = launch.val_of
        vo[B:] = launch.val_of[:1]
        carry = launch.init_fn(put(vo), put(ne))
    else:
        carry = launch.init_fn(put(ne))
    return _GroupState(
        launch=launch, padded_events=padded, chunk=chunk,
        scheduled=e_pad // chunk,
        slot_rows=slot_rows, carry=carry,
        ok=np.zeros((B,), dtype=bool), overflow=np.zeros((B,), dtype=bool),
        recorded=np.zeros((B,), dtype=bool), t_start=time.perf_counter())


def _chunk_slice(g: _GroupState, lo: int, width: int) -> np.ndarray:
    """[padded_B, width, 5] host slice for the next launch: each slot's
    mapped row's events (zeros for pad slots — EV_PAD no-ops)."""
    rows = np.maximum(g.slot_rows, 0)
    # Advanced indexing already materializes a fresh array, so the pad
    # slots can be zeroed in place.
    ev = g.padded_events[rows, lo:lo + width]
    if (g.slot_rows < 0).any():
        ev[g.slot_rows < 0] = 0
    return ev


def _span_chunks(g: _GroupState) -> int:
    """How many chunks the next launch coalesces. Flag syncs only pay
    for themselves at boundaries where a row can actually retire, and
    exhaustion is host-predictable: `n_events` is host data, so no live
    row can exhaust before min(alive `n_events`). The span therefore
    jumps to the first possible-retirement boundary in ONE launch
    instead of one launch per chunk — on the north-star shape that
    collapses ~11 sync-free launches per group into 2, and per-launch
    dispatch overhead (multi-device rendezvous, flag readback) was
    measured to eat the entire eviction win when paid per chunk. The
    span is rounded DOWN to a power-of-two multiple of the chunk so
    launch shapes stay in a bounded set ({chunk·2^k} × the row-bucket
    series) that hits the jit cache across groups. Soundness: a
    `decided` (~ok) row inside a coalesced span is caught at the next
    sync — its verdict is frozen (see module docstring), so it is
    recorded late, never differently; only eviction latency moves."""
    chunk = g.chunk
    live = g.slot_rows[g.slot_rows >= 0]
    live = live[~g.recorded[live]]
    lo = g.cursor * chunk
    first = int(g.launch.n_events[live].min()) if live.size else 0
    p = max(1, -(-(first - lo) // chunk))  # ceil, ≥1 once overdue
    p = min(p, g.scheduled - g.cursor)
    return 1 << (p.bit_length() - 1) if p > 1 else 1


def _dispatch(g: _GroupState) -> None:
    import jax

    span = _span_chunks(g)
    ev = _chunk_slice(g, g.cursor * g.chunk, span * g.chunk)
    if g.launch.device is not None:
        ev = jax.device_put(ev, g.launch.device)
    t0 = time.perf_counter()
    g.pending = (t0, span, g.launch.step_fn(g.carry, ev))


def _collect(g: _GroupState) -> None:
    """Block for the pending launch, record finished rows, evict, and
    recompact survivors when they fit a smaller row bucket."""
    import jax

    t_disp, span, (carry, decided, exhausted, ok, overflow) = g.pending
    g.pending = None
    g.carry = carry
    # blocks: device → host (the wavefront's per-round sync point)
    decided = np.asarray(decided)      # lint: allow(host-sync)
    exhausted = np.asarray(exhausted)  # lint: allow(host-sync)
    ok = np.asarray(ok)                # lint: allow(host-sync)
    overflow = np.asarray(overflow)    # lint: allow(host-sync)
    g.intervals.append((t_disp, time.perf_counter()))
    g.cursor += span
    g.launches_run += 1

    real = g.slot_rows >= 0
    finished = (decided | exhausted) & real
    rows = g.slot_rows[finished]
    fresh = rows[~g.recorded[rows]]
    if fresh.size:
        pos = np.flatnonzero(finished)[~g.recorded[rows]]
        g.ok[fresh] = ok[pos]
        g.overflow[fresh] = overflow[pos]
        g.recorded[fresh] = True
        if g.cursor < g.scheduled:
            g.evicted += int(fresh.size)

    alive = np.flatnonzero(real & ~(decided | exhausted))
    alive = alive[~g.recorded[g.slot_rows[alive]]]
    if alive.size == 0 or g.cursor >= g.scheduled:
        # Defensive tail: every row's events fit the schedule, so an
        # un-recorded row at schedule end cannot happen — but if it did,
        # its current verdict is the monolithic one (only EV_PAD left).
        left = g.slot_rows[alive] if alive.size else \
            np.empty((0,), np.int32)
        for p, r in zip(alive, left):
            if not g.recorded[r]:
                g.ok[r], g.overflow[r] = ok[p], overflow[p]
                g.recorded[r] = True
        g.done = True
        g.early_exit = g.cursor < g.scheduled
        g.wall_s = time.perf_counter() - g.t_start
        return

    if g.launch.exact_rows:
        return  # no recompaction (see ChunkLaunch.exact_rows)
    bucket = _bucket_launch_rows(g.launch, int(alive.size))
    if bucket < g.slot_rows.shape[0]:
        idx = _pad_idx([int(p) for p in alive], bucket)
        g.carry = jax.tree_util.tree_map(lambda x: x[idx], g.carry)
        if g.launch.device is not None:
            # Re-pin the gathered carry to the launch placement: the
            # eager gather does not preserve the batch-axis sharding,
            # and the next step_fn call must split evenly again.
            g.carry = jax.device_put(g.carry, g.launch.device)
        new_rows = np.full((bucket,), -1, dtype=np.int32)
        new_rows[: alive.size] = g.slot_rows[alive]
        g.slot_rows = new_rows


def _overlap_seconds(intervals: List[tuple]) -> float:
    """Total wall time during which ≥2 group chunks were in flight —
    estimated from (dispatch, collect) spans; collects happen in round
    order so this is an upper-bound estimate, reported as such."""
    events = []
    for a, b in intervals:
        events.append((a, 1))
        events.append((b, -1))
    events.sort()
    depth = 0
    overlap = 0.0
    prev = None
    for t, d in events:
        if prev is not None and depth >= 2:
            overlap += t - prev
        depth += d
        prev = t
    return overlap


def run_chunked(launches: List[ChunkLaunch],
                chunk: Optional[int] = None,
                record_stats: bool = True) -> List[GroupOutcome]:
    """Run window groups through the chunked wavefront; one
    GroupOutcome per launch, in order. Each round dispatches every live
    group's next chunk before blocking on any result, so group kernels
    overlap on their per-group devices (JAX async dispatch).
    A launch's own `chunk` field overrides the run-wide `chunk`
    (autotuned per-group plans). `record_stats=False` keeps a run out
    of the process/scope counters — the autotuner's short candidate
    samples must not inflate the eviction evidence bench.py and the
    per-run stores report."""
    chunk = scan_chunk() if chunk is None else chunk
    if chunk <= 0 and not (launches and all(ln.chunk for ln in launches)):
        raise ValueError("run_chunked needs a positive chunk size "
                         "(JGRAFT_SCAN_CHUNK=0 selects the legacy "
                         "monolithic path at the call site; per-launch "
                         "ChunkLaunch.chunk overrides may substitute)")
    groups = [_init_group(ln, chunk) for ln in launches]
    for g in groups:
        _dispatch(g)
    while True:
        live = [g for g in groups if not g.done]
        if not live:
            break
        for g in live:
            _collect(g)
            if not g.done:
                # Refill this launch's device queue BEFORE collecting
                # the next one (streaming, not bulk-synchronous): a
                # round barrier would drain every device queue while
                # the host walks the collect order, and the bubble is
                # pure loss on both the tunnel and the host.
                _dispatch(g)
    all_spans = [iv for g in groups for iv in g.intervals]
    if record_stats:
        _add_stats(chunks_run=sum(g.launches_run for g in groups),
                   evicted_rows=sum(g.evicted for g in groups),
                   groups_run=len(groups),
                   groups_early_exited=sum(1 for g in groups
                                           if g.early_exit),
                   pipeline_overlap_s=_overlap_seconds(all_spans))
    return [GroupOutcome(ok=g.ok, overflow=g.overflow, wall_s=g.wall_s,
                         chunks_run=g.launches_run, evicted_rows=g.evicted,
                         early_exit=g.early_exit, tag=g.launch.tag)
            for g in groups]


# ------------------------------------------------------- streaming carry


#: Sentinel event budget for a stream carry: the session does not know
#: its total event count, so `exhausted` (events_left ≤ 0) must never
#: fire — retirement is decided by the session (decided flag / finish).
STREAM_EVENTS_SENTINEL = 1 << 30

#: Events per carried launch while catching a backlog up (the per-append
#: suffix is usually far smaller and rides one padded launch).
STREAM_FEED_CHUNK = 1024


class CarriedScan:
    """Re-entrant chunk carry for ONE streamed history row (ISSUE 12).

    The chunked wavefront's carry (`ops/kernel_ir.chunk_step_fns`:
    ``{inner, left}`` + decided/exhausted flags) already makes the scan
    re-enterable at any chunk boundary; this class owns that carry
    ACROSS appends of a streaming session instead of across chunks of
    one launch. Each `feed` advances the identical `scan_step` sequence
    the monolithic kernel would run over the concatenated stream —
    suffix padding is EV_PAD no-op rows — so after feeding the whole
    stream the (ok, overflow) pair is bitwise-identical to the one-shot
    scan (the §14 soundness argument; chunk-chaining half pinned by
    tests/test_kernel_ir.py, the cross-append half by
    tests/test_stream.py).

    Flags follow the frozen-verdict rule: `ok` is monotone, so the
    moment it flips False mid-stream the verdict INVALID (or, with
    `overflow`, escalate-to-host) is FINAL — no later append can
    resurrect a dead frontier. That is what lets a session surface a
    violation at the earliest deciding segment and then evict the row.

    The kernel window is fixed at build time (`bucket_slots`); a
    session whose window outgrows it rebuilds a wider carry and
    re-feeds the accumulated stream (deterministic, so the rebuilt
    carry equals an uninterrupted wider scan). `fits()` answers whether
    a rebuild is needed.
    """

    def __init__(self, model, n_slots: int,
                 n_configs: Optional[int] = None):
        from ..ops.linear_scan import (DEFAULT_N_CONFIGS, bucket_slots,
                                       make_sort_chunk_checker)

        self.model = model
        self.n_configs = int(n_configs or DEFAULT_N_CONFIGS)
        # raises ValueError past MAX_SLOTS — the session escalates
        self.slots_cap = bucket_slots(max(int(n_slots), 1))
        init_fn, self._step = make_sort_chunk_checker(
            model, self.n_configs, self.slots_cap)
        self.carry = init_fn(
            np.asarray([STREAM_EVENTS_SENTINEL], np.int32))
        self.fed = 0          # events consumed (pre-padding)
        self.launches = 0
        self.ok = True
        self.overflow = False

    @property
    def decided(self) -> bool:
        """Frozen-verdict retirement: ~ok is final mid-stream."""
        return not self.ok

    def fits(self, n_slots: int) -> bool:
        return int(n_slots) <= self.slots_cap

    def feed(self, events: np.ndarray) -> None:
        """Advance the carry over an event suffix ([n, 5] int32).
        Stops early (evicts) the moment the row decides — the remaining
        suffix cannot change a frozen verdict."""
        n = int(events.shape[0])
        lo = 0
        while lo < n and not self.decided:
            span = events[lo:lo + STREAM_FEED_CHUNK]
            lo += span.shape[0]
            pad = bucket_rows(span.shape[0], 32)
            if pad != span.shape[0]:
                padded = np.zeros((pad, 5), dtype=np.int32)
                padded[: span.shape[0]] = span
                span = padded
            carry, _dec, _exh, ok, overflow = self._step(
                self.carry, span[None, :, :])
            self.carry = carry
            # blocks: device → host (the per-append sync point)
            self.ok = bool(np.asarray(ok)[0])  # lint: allow(host-sync)
            self.overflow = bool(
                np.asarray(overflow)[0])       # lint: allow(host-sync)
            self.launches += 1
        self.fed += n

"""CPU reference implementation of the frontier linearizability search.

This is the host-side twin of the TPU kernel (ops/linear_scan.py): the same
algorithm — scan the packed event stream, expand the frontier of
(linearized-bitmask, model-state) configurations to a fixed point at each
FORCE event, kill configurations that missed a forced op — implemented with
python sets and unbounded ints. It serves three roles:

  1. differential oracle for the TPU kernel (same events in, same verdict
     out — pinned by tests);
  2. fallback when a history exceeds the kernel's window/frontier capacity
     (masks here are arbitrary-precision, frontiers grow unbounded);
  3. counterexample reporting: on failure, the index of the op whose
     completion emptied the frontier, plus a witness linearization prefix.

Algorithm lineage: Wing & Gong linear search with Lowe's memoization
(what knossos' :linear algorithm does, reference register.clj:110-111),
reshaped from DFS-with-undo into a breadth/frontier form whose per-event
work is a pure set-expansion — the shape that maps onto SIMD.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..history.packing import EV_FORCE, EV_OPEN, EncodedHistory


@dataclass
class CpuCheckResult:
    valid: bool
    configs_explored: int = 0
    max_frontier: int = 0
    #: history index of the op whose ok-completion emptied the frontier.
    failing_op_index: Optional[int] = None
    #: op indices (history order) of one maximal linearization prefix —
    #: a witness order on success, the longest surviving prefix on failure.
    witness: Optional[list] = None


class FrontierOverflow(Exception):
    """Frontier exceeded the configured capacity."""

    def __init__(self, size: int):
        super().__init__(f"frontier overflow: {size} configurations")
        self.size = size


def check_encoded_cpu(
    enc: EncodedHistory,
    model,
    max_configs: Optional[int] = None,
    witness: bool = False,
) -> CpuCheckResult:
    """Run the frontier search on one encoded history.

    max_configs bounds the frontier (None = unbounded); exceeding it raises
    FrontierOverflow so callers can escalate rather than mis-report.
    """

    # frontier: (mask, state) -> node id into `nodes`;
    # nodes[i] = (parent node id, op index linearized on that edge).
    nodes: list = [(-1, -1)]
    frontier: dict = {(0, model.init_state()): 0}
    slot_ops: dict = {}
    open_slots: set = set()
    explored = 0
    max_front = 1
    events = enc.events
    step = model.step

    for ei in range(enc.n_events):
        etype, slot = int(events[ei, 0]), int(events[ei, 1])
        if etype == EV_OPEN:
            slot_ops[slot] = (
                int(events[ei, 2]),
                int(events[ei, 3]),
                int(events[ei, 4]),
                int(enc.op_index[ei]),
            )
            open_slots.add(slot)
        elif etype == EV_FORCE:
            # Closure: expand until no new configurations appear.
            stack = list(frontier.items())
            while stack:
                (mask, state), node = stack.pop()
                for j in open_slots:
                    if (mask >> j) & 1:
                        continue
                    fj, aj, bj, oi = slot_ops[j]
                    state2, legal = step(state, fj, aj, bj)
                    if not legal:
                        continue
                    cfg2 = (mask | (1 << j), state2)
                    if cfg2 not in frontier:
                        nodes.append((node, oi))
                        frontier[cfg2] = len(nodes) - 1
                        stack.append((cfg2, len(nodes) - 1))
                        explored += 1
                        if max_configs and len(frontier) > max_configs:
                            raise FrontierOverflow(len(frontier))
            max_front = max(max_front, len(frontier))
            # Survivors linearized the forced op; recycle its slot bit.
            # Clearing the bit can merge configs; keep either witness chain.
            bit = 1 << slot
            survivors: dict = {}
            for (mask, state), node in frontier.items():
                if mask & bit:
                    survivors.setdefault((mask & ~bit, state), node)
            if not survivors:
                return CpuCheckResult(
                    valid=False,
                    configs_explored=explored,
                    max_frontier=max_front,
                    failing_op_index=int(enc.op_index[ei]),
                    witness=_walk(nodes, _deepest(nodes, frontier))
                    if witness
                    else None,
                )
            frontier = survivors
            open_slots.discard(slot)

    return CpuCheckResult(
        valid=True,
        configs_explored=explored,
        max_frontier=max_front,
        witness=_walk(nodes, _deepest(nodes, frontier)) if witness else None,
    )


def _deepest(nodes, frontier) -> int:
    """Node whose linearization chain is longest (best witness)."""
    depth: dict = {-1: 0}

    def d(n: int) -> int:
        path = []
        while n not in depth:
            path.append(n)
            n = nodes[n][0]
        base = depth[n]
        for m in reversed(path):
            base += 1
            depth[m] = base
        return base if path else depth[n]

    return max(frontier.values(), key=d, default=0)


def _walk(nodes, node: int) -> list:
    chain = []
    while node > 0:
        parent, oi = nodes[node]
        chain.append(oi)
        node = parent
    return list(reversed(chain))

"""Derived (non-search) verdicts for the set and queue scenarios.

Jepsen pairs its set/queue workloads with cheap whole-history analyses
beside the expensive order-sensitive checker — `checker/set-full`'s
lost/stale elements, `checker/total-queue`'s conservation laws. These
are linear scans: they cannot replace the frontier search (they ignore
op order), but they answer the operator's first question — *what was
lost, exactly?* — and they stay cheap at production scale.

`SetAnalysis` (grow-only set, workload/set.py):
  * lost      — element whose add completed ok BEFORE the final read
                was invoked, yet absent from that read. Real-time
                ordering makes this a definite data-loss witness.
  * stale     — element observed by an earlier read, absent from a
                later read that began after the earlier one completed:
                a grow-only set moved backwards.
  * recovered — info (indefinite) adds that nevertheless appear in the
                final read — the honest-indefiniteness bookkeeping.

`QueueConservation` (ticket FIFO, workload/queue.py):
  * double-delivery — a ticket dequeued ok more than once.
  * phantom         — a ticket dequeued ok that no enqueue (ok or info)
                      could have produced (beyond the issued range).
  * lost            — a ticket from an ok enqueue never dequeued ok
                      although dequeues drained past it... is NOT
                      derivable order-free; what IS sound is range
                      accounting: ok-dequeues ≤ ok+info enqueues. The
                      order-sensitive FIFO property itself belongs to
                      the TicketQueue frontier model.

Both attach counts and the offending element/ticket lists, so a `fail`
carries its evidence inline (the linearizable checker's counterexample
minimization covers the order-sensitive side).
"""

from __future__ import annotations

from ..history.ops import History, pair_ops_indexed
from .base import Checker, INVALID, VALID


class SetAnalysis(Checker):
    """Lost/stale-element analysis for the grow-only set workload."""

    def check(self, test, history, opts=None) -> dict:
        if not isinstance(history, History):
            history = History(history)
        ops = list(history.client_ops())
        adds_ok: dict = {}    # element -> completion position
        adds_info: set = set()
        attempts = 0
        reads = []            # (invoke_pos, completion_pos, frozenset)
        for ip, cp, inv, comp in pair_ops_indexed(ops):
            ctype = comp.type if comp is not None else "info"
            if inv.f == "add":
                attempts += 1
                e = int(inv.value)
                if ctype == "ok":
                    # EARLIEST completion per element: lost-ness keys on
                    # "some ack landed before the final read began", so
                    # a later duplicate add must not mask an earlier ack
                    # (pairs arrive in invoke order, not completion
                    # order).
                    adds_ok[e] = min(adds_ok.get(e, cp), cp)
                elif ctype == "info":
                    adds_info.add(e)
            elif inv.f == "read" and ctype == "ok":
                elems = comp.value
                if isinstance(elems, int):
                    elems = [i for i in range(32) if (elems >> i) & 1]
                reads.append((ip, cp, frozenset(int(e) for e in elems)))
        if not reads:
            return {"valid?": VALID, "attempt-count": attempts,
                    "ok-count": len(adds_ok), "read-count": 0,
                    "note": "no completed reads — nothing to compare"}
        final = max(reads, key=lambda r: r[1])
        lost = sorted(e for e, cpos in adds_ok.items()
                      if cpos < final[0] and e not in final[2])
        # stale: sweep reads by invoke order; `settled` holds elements
        # some read completed observing before this read began — a
        # grow-only set must keep showing them. One incremental union
        # over a completion-ordered pointer keeps the scan O(R log R).
        stale: set = set()
        by_completion = sorted(reads, key=lambda r: r[1])
        settled: set = set()
        done = 0
        for ip, cp, elems in sorted(reads):
            while done < len(by_completion) and \
                    by_completion[done][1] < ip:
                settled |= by_completion[done][2]
                done += 1
            stale |= settled - elems
        recovered = sorted(adds_info & final[2])
        valid = not lost and not stale
        out = {
            "valid?": VALID if valid else INVALID,
            "attempt-count": attempts,
            "ok-count": len(adds_ok),
            "read-count": len(reads),
            "lost": lost,
            "stale": sorted(stale),
            "recovered": recovered,
            "final-read-size": len(final[2]),
        }
        if not valid:
            out["explanation"] = (
                f"grow-only set lost {len(lost)} and un-grew "
                f"{len(stale)} element(s): lost={lost} "
                f"stale={sorted(stale)}")
        return out


class QueueConservation(Checker):
    """Order-free conservation laws for the ticket-FIFO workload."""

    def check(self, test, history, opts=None) -> dict:
        if not isinstance(history, History):
            history = History(history)
        ops = list(history.client_ops())
        enq_ok: set = set()
        enq_attempts = 0
        enq_info = 0
        deq_counts: dict = {}
        empties = 0
        for ip, cp, inv, comp in pair_ops_indexed(ops):
            ctype = comp.type if comp is not None else "info"
            if inv.f == "enqueue":
                enq_attempts += 1
                if ctype == "ok":
                    enq_ok.add(int(comp.value))
                elif ctype == "info":
                    enq_info += 1
            elif inv.f == "dequeue" and ctype == "ok":
                if comp.value is None:
                    empties += 1
                else:
                    t = int(comp.value)
                    deq_counts[t] = deq_counts.get(t, 0) + 1
        double = sorted(t for t, n in deq_counts.items() if n > 1)
        issued = len(enq_ok) + enq_info  # upper bound on real tickets
        phantom = sorted(t for t in deq_counts
                         if t not in enq_ok and t >= issued)
        valid = not double and not phantom
        out = {
            "valid?": VALID if valid else INVALID,
            "enqueue-attempts": enq_attempts,
            "enqueue-ok": len(enq_ok),
            "enqueue-info": enq_info,
            "dequeue-ok": sum(deq_counts.values()),
            "dequeue-empty": empties,
            "double-delivery": double,
            "phantom": phantom,
        }
        if not valid:
            out["explanation"] = (
                f"queue conservation violated: double-delivery={double} "
                f"phantom={phantom}")
        return out

"""Per-process operation timeline, rendered to HTML.

Equivalent of jepsen.checker.timeline/html (reference register.clj:108,
counter.clj:134, leader.clj:82): one swimlane per process, one box per op
spanning invocation→completion, colored by completion type. Written into
the store directory when available.
"""

from __future__ import annotations

import html as html_mod
from pathlib import Path

from ..history.ops import FAIL, INFO, OK, History
from .base import Checker

_COLORS = {OK: "#9ce29c", FAIL: "#f5a3a3", INFO: "#ffd27f"}


class TimelineChecker(Checker):
    def __init__(self, filename: str = "timeline.html"):
        self.filename = filename

    def check(self, test, history, opts=None) -> dict:
        if not isinstance(history, History):
            history = History(history)
        doc = render_timeline(history)
        out = {"valid?": True}
        store_dir = (test or {}).get("store_dir")
        if store_dir:
            path = Path(store_dir) / self.filename
            try:
                path.write_text(doc)
                out["file"] = str(path)
            except OSError:
                pass
        else:
            out["html"] = doc
        return out


def render_timeline(history: History, px_per_s: float = 100.0) -> str:
    pairs = history.client_ops().pairs()
    if not pairs:
        return "<html><body>empty history</body></html>"
    tmax = max((p.completion.time for p in pairs if p.completion is not None),
               default=0)
    procs = sorted({p.invoke.process for p in pairs},
                   key=lambda x: (str(type(x)), x))
    lane = {p: i for i, p in enumerate(procs)}
    rows = []
    for p in pairs:
        t0 = p.invoke.time / 1e9
        t1 = (p.completion.time if p.completion is not None else tmax) / 1e9
        typ = p.ctype
        left = 80 + t0 * px_per_s
        width = max(2.0, (t1 - t0) * px_per_s)
        top = 10 + lane[p.invoke.process] * 26
        label = html_mod.escape(
            f"{p.f} {p.invoke.value!r} -> {typ}"
            + (f" {p.completion.value!r}" if p.completion is not None else ""))
        rows.append(
            f"<div class='op' title='{label}' style='left:{left:.0f}px;"
            f"top:{top}px;width:{width:.0f}px;"
            f"background:{_COLORS.get(typ, '#ddd')}'>{html_mod.escape(str(p.f))}"
            f"</div>")
    lanes = "".join(
        f"<div class='lane' style='top:{10 + i * 26}px'>{html_mod.escape(str(pr))}</div>"
        for pr, i in lane.items())
    height = 40 + len(procs) * 26
    return (
        "<html><head><style>"
        ".op{position:absolute;height:20px;font-size:10px;overflow:hidden;"
        "border:1px solid #555;border-radius:3px;padding:0 2px;}"
        ".lane{position:absolute;left:0;width:75px;font:11px sans-serif;"
        "text-align:right;}"
        "body{position:relative;font-family:sans-serif;}"
        f"</style></head><body style='height:{height}px'>"
        f"{lanes}{''.join(rows)}</body></html>")

"""Per-process operation timeline, rendered to HTML.

Equivalent of jepsen.checker.timeline/html (reference register.clj:108,
counter.clj:134, leader.clj:82): one swimlane per process, one box per op
spanning invocation→completion, colored by completion type. Written into
the store directory when available.
"""

from __future__ import annotations

import html as html_mod
from pathlib import Path

from ..history.ops import FAIL, INFO, OK, History
from .base import Checker

_COLORS = {OK: "#9ce29c", FAIL: "#f5a3a3", INFO: "#ffd27f"}


class TimelineChecker(Checker):
    def __init__(self, filename: str = "timeline.html"):
        self.filename = filename

    def check(self, test, history, opts=None) -> dict:
        if not isinstance(history, History):
            history = History(history)
        doc = render_timeline(history)
        out = {"valid?": True}
        store_dir = (test or {}).get("store_dir")
        if store_dir:
            path = Path(store_dir) / self.filename
            try:
                path.write_text(doc)
                out["file"] = str(path)
            except OSError:
                pass
        else:
            out["html"] = doc
        return out


def render_timeline(history: History, px_per_s: float = 100.0,
                    highlight_index: int | None = None,
                    footer_html: str = "") -> str:
    """Render the swimlane timeline. `highlight_index` marks the op pair
    whose invoke or completion has that history index as the violating op
    (thick red outline) — counterexample rendering, the analogue of the
    anomaly graphs the reference's stack renders via graphviz
    (reference bin/docker/control/Dockerfile:13-14)."""
    pairs = history.client_ops().pairs()
    if not pairs:
        return "<html><body>empty history</body></html>"
    tmax = max((p.completion.time for p in pairs if p.completion is not None),
               default=0)
    procs = sorted({p.invoke.process for p in pairs},
                   key=lambda x: (str(type(x)), x))
    lane = {p: i for i, p in enumerate(procs)}
    rows = []
    for p in pairs:
        t0 = p.invoke.time / 1e9
        t1 = (p.completion.time if p.completion is not None else tmax) / 1e9
        typ = p.ctype
        left = 80 + t0 * px_per_s
        width = max(2.0, (t1 - t0) * px_per_s)
        top = 10 + lane[p.invoke.process] * 26
        label = html_mod.escape(
            f"{p.f} {p.invoke.value!r} -> {typ}"
            + (f" {p.completion.value!r}" if p.completion is not None else ""))
        hot = highlight_index is not None and (
            p.invoke.index == highlight_index
            or (p.completion is not None
                and p.completion.index == highlight_index))
        cls = "op bad" if hot else "op"
        rows.append(
            f"<div class='{cls}' title='{label}' style='left:{left:.0f}px;"
            f"top:{top}px;width:{width:.0f}px;"
            f"background:{_COLORS.get(typ, '#ddd')}'>{html_mod.escape(str(p.f))}"
            f"</div>")
    lanes = "".join(
        f"<div class='lane' style='top:{10 + i * 26}px'>{html_mod.escape(str(pr))}</div>"
        for pr, i in lane.items())
    height = 40 + len(procs) * 26
    return (
        "<html><head><style>"
        ".op{position:absolute;height:20px;font-size:10px;overflow:hidden;"
        "border:1px solid #555;border-radius:3px;padding:0 2px;}"
        ".op.bad{border:3px solid #c00;z-index:2;box-shadow:0 0 6px #c00;}"
        ".lane{position:absolute;left:0;width:75px;font:11px sans-serif;"
        "text-align:right;}"
        ".footer{position:absolute;left:0;font:12px sans-serif;"
        "white-space:pre-wrap;}"
        "body{position:relative;font-family:sans-serif;}"
        f"</style></head><body style='height:{height + 20}px'>"
        f"{lanes}{''.join(rows)}"
        + (f"<div class='footer' style='top:{height}px'>{footer_html}</div>"
           if footer_html else "")
        + "</body></html>")

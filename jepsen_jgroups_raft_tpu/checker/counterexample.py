"""Counterexample presentation for invalid linearizability verdicts.

The reference's stack doesn't stop at "false": knossos emits linearization
diagrams and the control image ships graphviz to render anomalies
(reference bin/docker/control/Dockerfile:13-14). This module is that
capability for this framework: given an INVALID verdict, it

  1. recovers a machine-checkable explanation — the failing op (the
     completion at which no linearization order survives) and a witness
     prefix (one maximal legal linearization) — re-running the unbounded
     CPU frontier with witness tracking when the deciding engine (e.g. the
     TPU kernel) didn't produce one;
  2. inlines a human-readable `counterexample` dict into the result map
     (store/results.json picks it up verbatim);
  3. renders `counterexample[-<key>].html` into the run's store dir: the
     op timeline with the violating op highlighted and the witness prefix
     listed below.
"""

from __future__ import annotations

import html as html_mod
from pathlib import Path
from typing import Optional

from ..history.ops import History, Op, pair_ops_indexed
from ..history.packing import encode_history
from .base import INVALID
from .timeline import render_timeline
from .wgl_cpu import FrontierOverflow, check_encoded_cpu

#: Minimization budget: the greedy pair-drop pass re-checks the history
#: once per candidate pair on the capped CPU frontier, so both knobs
#: bound worst-case minimization time (≈ pairs × frontier cap). Above
#: the pair cap the suffix truncation still applies — it is free.
MINIMIZE_MAX_PAIRS = 64
MINIMIZE_MAX_CONFIGS = 1 << 14


def _op_view(op: Op) -> dict:
    return {"index": op.index, "process": op.process, "type": op.type,
            "f": op.f, "value": op.value}


def _index_map(history: History) -> dict:
    """history-index → Op, matching packing's op_index convention
    (op.index when set, list position otherwise)."""
    out = {}
    for i, op in enumerate(history):
        out[op.index if op.index >= 0 else i] = op
    return out


def _encode_at_rung(history: History, model,
                    consistency: Optional[str]):
    """Encode (and, for a weaker rung, relax) a history — the stream the
    deciding engine actually scanned, so explanations and minimization
    re-searches stay on the verdict's own precedence order."""
    enc = encode_history(history, model)
    if consistency not in (None, "linearizable"):
        from .consistency import relax_encoded

        enc = relax_encoded(enc, model, consistency)
    return enc


def attach_counterexample(result: dict, history: History, model,
                          max_cpu_configs: Optional[int] = None,
                          consistency: Optional[str] = None) -> dict:
    """Enrich an INVALID result with failing-op/witness details, a
    human-readable `counterexample` dict, and (when the budget allows) a
    MINIMIZED witness history — the production-scale contract: a fail
    verdict comes back as a small reproducer, not a raw op dump. No-op
    for valid/unknown. `consistency` names the rung that produced the
    verdict so the re-search scans the same relaxed stream."""
    if result.get("valid?") is not INVALID:
        return result
    if "failing-op-index" not in result:
        # The deciding engine (the TPU kernel) returned only the verdict;
        # recover the explanation on the CPU frontier. Host engines attach
        # failing-op-index during the verdict run, so this re-search only
        # happens for kernel-decided results.
        try:
            r = check_encoded_cpu(_encode_at_rung(history, model,
                                                  consistency), model,
                                  max_configs=max_cpu_configs, witness=True)
            if not r.valid:
                result.setdefault("failing-op-index", r.failing_op_index)
                if r.witness is not None:
                    result.setdefault("witness", r.witness)
        except FrontierOverflow:
            pass  # verdict stands; explanation unavailable at this budget

    by_index = _index_map(history)
    ce: dict = {}
    fi = result.get("failing-op-index")
    if fi is not None and fi in by_index:
        bad = by_index[fi]
        ce["failing-op"] = _op_view(bad)
        ce["explanation"] = (
            f"no linearization order satisfies the completion of "
            f"{bad.f} {bad.value!r} by process {bad.process} "
            f"(history index {fi}); every configuration that survived the "
            f"preceding ops is killed here")
    wit = result.get("witness")
    if wit is not None:
        ce["witness-prefix"] = [
            _op_view(by_index[i]) for i in wit if i in by_index
        ]
    if ce:
        result["counterexample"] = ce
    minimize_counterexample(result, history, model,
                            consistency=consistency)
    return result


def _still_invalid(ops, model, consistency) -> Optional[bool]:
    """Capped re-check of a candidate reduction; None = undecidable at
    the minimization budget (treated as 'keep the op')."""
    try:
        r = check_encoded_cpu(
            _encode_at_rung(History(list(ops)), model, consistency),
            model, max_configs=MINIMIZE_MAX_CONFIGS)
        return not r.valid
    except FrontierOverflow:
        return None


def minimize_counterexample(result: dict, history: History, model,
                            consistency: Optional[str] = None) -> dict:
    """Shrink an INVALID history to a small reproducer, in two sound
    passes:

      1. Suffix truncation (verified): the frontier died at the failing
         op's completion, so at the LINEARIZABLE rung nothing after
         that event participated — but at a weaker rung the deciding
         FORCE was DEFERRED past later ops' opens, which may have
         constrained the frontier, so the truncation is re-checked on
         the capped CPU frontier at the same rung and kept only when
         the prefix is still invalid (otherwise the full history stays).
      2. Greedy pair-drop (budgeted): for each remaining op pair except
         the failing one, re-check the history without it the same way;
         a pair whose removal keeps the verdict INVALID is removed for
         good. Each kept reduction preserves invalidity by direct
         re-check, so the final set is a genuine counterexample
         (1-minimal under the budget, not globally minimal).

    Attaches ``counterexample.minimal-ops`` / ``minimal-op-count`` when
    some verified reduction landed; skips silently when the failing op
    is unknown or nothing could be (affordably) verified smaller."""
    fi = result.get("failing-op-index")
    if fi is None:
        return result
    ops = list(history)
    comp_pos = None
    for i, op in enumerate(ops):
        idx = op.index if op.index >= 0 else i
        if idx == fi and op.is_completion():
            comp_pos = i
    if comp_pos is None:
        return result
    prefix = ops[:comp_pos + 1]
    failing_op = ops[comp_pos]
    reduced = False
    if len(prefix) < len(ops) and \
            _still_invalid(prefix, model, consistency):
        reduced = True
    else:
        prefix = ops
    pairs = pair_ops_indexed(prefix)
    if len(pairs) <= MINIMIZE_MAX_PAIRS:
        removed: set = set()
        for ip, cp, inv, comp in pairs:
            if cp >= 0 and prefix[cp] is failing_op:
                continue  # never drop the failing op itself
            trial = removed | ({ip, cp} - {-1})
            kept = [op for j, op in enumerate(prefix) if j not in trial]
            if _still_invalid(kept, model, consistency):
                removed = trial
        if removed:
            reduced = True
            prefix = [op for j, op in enumerate(prefix)
                      if j not in removed]
    if not reduced:
        return result
    ce = result.setdefault("counterexample", {})
    ce["minimal-ops"] = [_op_view(op) for op in prefix]
    ce["minimal-op-count"] = sum(1 for op in prefix if op.is_invoke())
    return result


def write_counterexample_html(result: dict, history: History,
                              store_dir, filename: str) -> Optional[str]:
    """Render the highlighted timeline + witness into the store dir."""
    if result.get("valid?") is not INVALID or not store_dir:
        return None
    ce = result.get("counterexample", {})
    lines = []
    if "explanation" in ce:
        lines.append("VIOLATION: " + ce["explanation"])
    for v in ce.get("witness-prefix", []):
        lines.append(
            f"  linearized: [{v['index']}] proc {v['process']} "
            f"{v['f']} {v['value']!r}")
    footer = html_mod.escape("\n".join(lines))
    doc = render_timeline(history,
                          highlight_index=result.get("failing-op-index"),
                          footer_html=footer)
    path = Path(store_dir) / filename
    try:
        path.write_text(doc)
    except OSError:
        return None
    result.setdefault("counterexample", {})["file"] = str(path)
    return str(path)

"""Counterexample presentation for invalid linearizability verdicts.

The reference's stack doesn't stop at "false": knossos emits linearization
diagrams and the control image ships graphviz to render anomalies
(reference bin/docker/control/Dockerfile:13-14). This module is that
capability for this framework: given an INVALID verdict, it

  1. recovers a machine-checkable explanation — the failing op (the
     completion at which no linearization order survives) and a witness
     prefix (one maximal legal linearization) — re-running the unbounded
     CPU frontier with witness tracking when the deciding engine (e.g. the
     TPU kernel) didn't produce one;
  2. inlines a human-readable `counterexample` dict into the result map
     (store/results.json picks it up verbatim);
  3. renders `counterexample[-<key>].html` into the run's store dir: the
     op timeline with the violating op highlighted and the witness prefix
     listed below.
"""

from __future__ import annotations

import html as html_mod
from pathlib import Path
from typing import Optional

from ..history.ops import History, Op
from ..history.packing import encode_history
from .base import INVALID
from .timeline import render_timeline
from .wgl_cpu import FrontierOverflow, check_encoded_cpu


def _op_view(op: Op) -> dict:
    return {"index": op.index, "process": op.process, "type": op.type,
            "f": op.f, "value": op.value}


def _index_map(history: History) -> dict:
    """history-index → Op, matching packing's op_index convention
    (op.index when set, list position otherwise)."""
    out = {}
    for i, op in enumerate(history):
        out[op.index if op.index >= 0 else i] = op
    return out


def attach_counterexample(result: dict, history: History, model,
                          max_cpu_configs: Optional[int] = None) -> dict:
    """Enrich an INVALID result with failing-op/witness details and a
    human-readable `counterexample` dict. No-op for valid/unknown."""
    if result.get("valid?") is not INVALID:
        return result
    if "failing-op-index" not in result:
        # The deciding engine (the TPU kernel) returned only the verdict;
        # recover the explanation on the CPU frontier. Host engines attach
        # failing-op-index during the verdict run, so this re-search only
        # happens for kernel-decided results.
        try:
            r = check_encoded_cpu(encode_history(history, model), model,
                                  max_configs=max_cpu_configs, witness=True)
            if not r.valid:
                result.setdefault("failing-op-index", r.failing_op_index)
                if r.witness is not None:
                    result.setdefault("witness", r.witness)
        except FrontierOverflow:
            pass  # verdict stands; explanation unavailable at this budget

    by_index = _index_map(history)
    ce: dict = {}
    fi = result.get("failing-op-index")
    if fi is not None and fi in by_index:
        bad = by_index[fi]
        ce["failing-op"] = _op_view(bad)
        ce["explanation"] = (
            f"no linearization order satisfies the completion of "
            f"{bad.f} {bad.value!r} by process {bad.process} "
            f"(history index {fi}); every configuration that survived the "
            f"preceding ops is killed here")
    wit = result.get("witness")
    if wit is not None:
        ce["witness-prefix"] = [
            _op_view(by_index[i]) for i in wit if i in by_index
        ]
    if ce:
        result["counterexample"] = ce
    return result


def write_counterexample_html(result: dict, history: History,
                              store_dir, filename: str) -> Optional[str]:
    """Render the highlighted timeline + witness into the store dir."""
    if result.get("valid?") is not INVALID or not store_dir:
        return None
    ce = result.get("counterexample", {})
    lines = []
    if "explanation" in ce:
        lines.append("VIOLATION: " + ce["explanation"])
    for v in ce.get("witness-prefix", []):
        lines.append(
            f"  linearized: [{v['index']}] proc {v['process']} "
            f"{v['f']} {v['value']!r}")
    footer = html_mod.escape("\n".join(lines))
    doc = render_timeline(history,
                          highlight_index=result.get("failing-op-index"),
                          footer_html=footer)
    path = Path(store_dir) / filename
    try:
        path.write_text(doc)
    except OSError:
        return None
    result.setdefault("counterexample", {})["file"] = str(path)
    return str(path)

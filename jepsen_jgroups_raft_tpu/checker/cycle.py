"""Exact dependency-cycle refutation tier (ISSUE 13).

The weaker-consistency rungs (checker/consistency.py) are interval-order
*relaxations*: a rung PASS certifies its guarantee, but a rung FAIL only
ever certifies non-linearizability — it is conservative about the
guarantee itself (exact SC checking is NP-hard; the relaxed intervals
still carry stream-order edges). This module adds the missing *exact
refutation* direction, the same machinery PAPER.md's checker ecosystem
reaches for in Elle: build the dependency graph whose edges every
sequentially-consistent execution must respect, and a cycle in it is a
sharp, witness-carrying proof that NO sequential order exists — not
merely that none fits the relaxed intervals.

Graph construction (register-shaped, via ``Model.rw_classify`` — the
hook's contract is last-writer-wins state, models/base.py):

  * **Required ops** — forced (ok-completed) ops always linearize.  An
    optional (crashed) op is pulled in only when it is the UNIQUE
    writer of a value some required reader observed — then it must have
    linearized too (nobody else could have produced the value), fixed-
    pointed across chains of optional CASes.  All other optional ops
    are excluded: an edge through an op that might not linearize proves
    nothing.
  * **SO** (session order): consecutive required ops of one process, in
    open order — program order binds every op that linearizes.
  * **WR** (reads-from): reader r observed v (≠ the initial value) and
    exactly ONE op w in the whole encoded history writes v ⇒ w → r.
    Values written more than once contribute no WR edges (conservative,
    never unsound).
  * **RW** (anti-dependency): r reads v from unique writer w, and w' is
    a required writer whose order after w is KNOWN (same process as w,
    later open) ⇒ r → w' — r must precede the overwrite, else the only
    writer of v sits before w' and the state at r could not be v.
  * **Reads-of-initial**: r observed the initial value and NO op writes
    it ⇒ r → every required writer (any write destroys the initial
    value for good).

Soundness (doc/checker-design.md §15): each edge u → v holds in every
legal sequential execution of the required ops, and any witness the
sequential rung's kernel accepts IS such an execution — so a cycle
implies the rung kernel must answer INVALID too (composed verdicts
stay exact; the cheap certifier only ever certifies VALID, this tier
only ever refutes).  At the *session* rung the implemented guarantee
(monotonic reads + read-your-writes) does NOT imply full session order
— a monotonic-writes violation can pass the rung — so there the cycle
is attached as an ``sc-refuted`` annotation instead of a verdict: exact
evidence the history is not sequentially consistent even though the
weaker rung honestly holds (the sharper-than-relaxation acceptance
row, pinned in tests/test_cycle.py).

Execution: adjacency matrices batch across rows (pow2-bucketed node
counts, zero-padded rows), and the transitive closure is the batched
int32 boolean-matmul squaring kernel of ops/kernel_ir.make_cycle_closure
— free where matmul is free.  Off-TPU the same adjacency runs a host
DFS instead (verdict-identical; the PLATFORM_ROUTE idiom —
``JGRAFT_CYCLE_KERNEL`` forces either arm for tests/ablation).
"""

from __future__ import annotations

import functools
import os
from typing import List, Optional, Sequence

import numpy as np

from ..history.packing import EV_FORCE, EV_OPEN, EncodedHistory
from ..ops.kernel_ir import CYCLE_MAX_NODES
from ..platform import env_int


def cycle_tier_on() -> bool:
    """Whether the exact cycle tier runs at the weak rungs
    (JGRAFT_CYCLE_TIER=0 disables — the ablation arm; verdicts must be
    identical either way at the sequential rung, pinned by tests)."""
    return env_int("JGRAFT_CYCLE_TIER", 1, minimum=0) != 0


def cycle_max_ops() -> int:
    """Per-row node cap (JGRAFT_CYCLE_MAX_OPS, default
    CYCLE_MAX_NODES): rows whose required-op graph is bigger skip the
    tier — the kernel ladder still decides them, so the cap only moves
    work, never answers."""
    return env_int("JGRAFT_CYCLE_MAX_OPS", CYCLE_MAX_NODES, minimum=1)


def _use_kernel() -> bool:
    """Closure-kernel routing: the batched matmul pass where matmul is
    effectively free (TPU), the O(V+E) host DFS everywhere else — same
    measured-routing stance as PLATFORM_ROUTE_MIN_CELLS.
    JGRAFT_CYCLE_KERNEL=1/0 forces the arm (tests, ablation)."""
    forced = os.environ.get("JGRAFT_CYCLE_KERNEL")
    if forced is not None:
        return forced == "1"
    import jax

    return jax.default_backend() == "tpu"


# ------------------------------------------------------ graph building


def build_sc_graph(enc: EncodedHistory, model) -> Optional[dict]:
    """Dependency graph of one encoded history, or None when the model
    cannot classify an op / the encoding has no per-event process ids /
    the required-op count exceeds the cap.  Returns {"n", "adj"
    ([n, n] uint8), "op_index" (node → original history op index)}."""
    classify = getattr(model, "rw_classify", None)
    if classify is None or enc.proc is None or enc.n_events == 0:
        return None
    events = enc.events
    ops: List[tuple] = []   # (f, a, b, pid, hist_index)
    forced: List[bool] = []
    active: dict = {}
    for pos in range(enc.n_events):
        et, slot = int(events[pos, 0]), int(events[pos, 1])
        if et == EV_OPEN:
            active[slot] = len(ops)
            ops.append((int(events[pos, 2]), int(events[pos, 3]),
                        int(events[pos, 4]), int(enc.proc[pos]),
                        int(enc.op_index[pos])))
            forced.append(False)
        elif et == EV_FORCE:
            forced[active.pop(slot)] = True
    cls: List[tuple] = []
    for f, a, b, _pid, _hi in ops:
        c = classify(f, a, b)
        if c is None:
            return None  # one unclassifiable op poisons every edge
        cls.append(c)

    def read_of(k):
        c = cls[k]
        return c[1] if c[0] in ("r", "rw") else None

    def write_of(k):
        c = cls[k]
        return c[2] if c[0] == "rw" else (c[1] if c[0] == "w" else None)

    initial = model.init_state()
    writers: dict = {}
    for k in range(len(ops)):
        wv = write_of(k)
        if wv is not None:
            writers.setdefault(wv, []).append(k)

    # required = forced ∪ (unique writers of required-observed values),
    # to a fixpoint across optional CAS chains
    required = {k for k in range(len(ops)) if forced[k]}
    wr_edges = set()
    frontier = list(required)
    while frontier:
        nxt = []
        for r in frontier:
            rv = read_of(r)
            if rv is None or rv == initial:
                continue
            ws = writers.get(rv, [])
            if len(ws) == 1 and ws[0] != r:
                w = ws[0]
                wr_edges.add((w, r))
                if w not in required:
                    required.add(w)
                    nxt.append(w)
        frontier = nxt
    if len(required) > cycle_max_ops():
        return None

    order = sorted(required)               # open order
    node = {k: i for i, k in enumerate(order)}
    n = len(order)
    adj = np.zeros((n, n), dtype=np.uint8)
    # SO: consecutive required ops per process
    last_of: dict = {}
    for k in order:
        pid = ops[k][3]
        if pid in last_of:
            adj[node[last_of[pid]], node[k]] = 1
        last_of[pid] = k
    req_writers = [k for k in order if write_of(k) is not None]
    for w, r in wr_edges:
        adj[node[w], node[r]] = 1
        # RW: r must precede every overwrite whose order after w is
        # known (same process as w, opened later)
        for w2 in req_writers:
            if w2 != w and w2 != r and ops[w2][3] == ops[w][3] \
                    and w2 > w:
                adj[node[r], node[w2]] = 1
    # reads-of-initial: no op writes the initial value ⇒ the reader
    # precedes every required writer
    if not writers.get(initial):
        for r in order:
            if read_of(r) == initial:
                for w2 in req_writers:
                    if w2 != r:
                        adj[node[r], node[w2]] = 1
    np.fill_diagonal(adj, 0)
    return {"n": n, "adj": adj,
            "op_index": [ops[k][4] for k in order]}


# ------------------------------------------------------ cycle detection


def host_has_cycle(adj: np.ndarray) -> bool:
    """Iterative 3-color DFS over a dense adjacency matrix — the
    NetworkX-free host oracle the closure kernel is differentially
    pinned against (and the off-TPU production arm)."""
    n = int(adj.shape[0])
    color = np.zeros(n, dtype=np.int8)  # 0 white, 1 gray, 2 black
    succ = [np.flatnonzero(adj[i]) for i in range(n)]
    for root in range(n):
        if color[root]:
            continue
        stack = [(root, 0)]
        color[root] = 1
        while stack:
            v, j = stack[-1]
            if j < len(succ[v]):
                stack[-1] = (v, j + 1)
                w = int(succ[v][j])
                if color[w] == 1:
                    return True
                if color[w] == 0:
                    color[w] = 1
                    stack.append((w, 0))
            else:
                color[v] = 2
                stack.pop()
    return False


def cycle_witness(adj: np.ndarray) -> Optional[List[int]]:
    """One concrete cycle (node list, closed implicitly) from a cyclic
    adjacency matrix: shortest cycle through the first node that can
    reach itself (BFS) — small, checkable evidence for the result
    record."""
    n = int(adj.shape[0])
    for start in range(n):
        # BFS from start's successors back to start
        prev = np.full(n, -1, dtype=np.int64)
        q = []
        for s in np.flatnonzero(adj[start]):
            prev[int(s)] = start
            q.append(int(s))
        qi = 0
        while qi < len(q):
            v = q[qi]
            qi += 1
            if adj[v, start]:
                # path start → ... → v (→ start implicitly)
                path = [v]
                while path[-1] != start:
                    path.append(int(prev[path[-1]]))
                path.reverse()
                return path
            for w in np.flatnonzero(adj[v]):
                w = int(w)
                if prev[w] < 0:
                    prev[w] = v
                    q.append(w)
    return None


@functools.lru_cache(maxsize=None)
def _closure_kernel(n_nodes: int):
    from ..ops.kernel_ir import make_cycle_closure

    return make_cycle_closure(n_nodes)


def find_cycles(encs: Sequence[EncodedHistory], model,
                kernel: Optional[bool] = None
                ) -> List[Optional[dict]]:
    """Per row: None (no graph / acyclic) or {"cycle": [history op
    indices...], "nodes": n} — an exact SC refutation witness.  Graphs
    batch by pow2-bucketed node count through the closure kernel on
    TPU; host DFS otherwise (identical answers, pinned by tests).
    `kernel` overrides the routing (False = host DFS even on TPU —
    graftd's device-degrade path must not launch)."""
    from ..history.packing import bucket_rows

    out: List[Optional[dict]] = [None] * len(encs)
    built = []
    for i, enc in enumerate(encs):
        g = build_sc_graph(enc, model)
        if g is not None and g["n"] >= 2:
            built.append((i, g))
    if not built:
        return out
    flags = {}
    if _use_kernel() if kernel is None else kernel:
        by_bucket: dict = {}
        for i, g in built:
            by_bucket.setdefault(bucket_rows(g["n"], 4), []).append((i, g))
        for N, rows in by_bucket.items():
            batch = np.zeros((len(rows), N, N), dtype=np.int32)
            for j, (_i, g) in enumerate(rows):
                batch[j, :g["n"], :g["n"]] = g["adj"]
            has, _closed = _closure_kernel(N)(batch)
            has = np.asarray(has)  # lint: allow(host-sync)
            for j, (i, _g) in enumerate(rows):
                flags[i] = bool(has[j])
    else:
        for i, g in built:
            flags[i] = host_has_cycle(g["adj"])
    for i, g in built:
        if flags.get(i):
            path = cycle_witness(g["adj"]) or []
            out[i] = {"cycle": [g["op_index"][v] for v in path],
                      "nodes": g["n"]}
    return out

"""Exact dependency-cycle refutation tier (ISSUE 13).

The weaker-consistency rungs (checker/consistency.py) are interval-order
*relaxations*: a rung PASS certifies its guarantee, but a rung FAIL only
ever certifies non-linearizability — it is conservative about the
guarantee itself (exact SC checking is NP-hard; the relaxed intervals
still carry stream-order edges). This module adds the missing *exact
refutation* direction, the same machinery PAPER.md's checker ecosystem
reaches for in Elle: build the dependency graph whose edges every
sequentially-consistent execution must respect, and a cycle in it is a
sharp, witness-carrying proof that NO sequential order exists — not
merely that none fits the relaxed intervals.

Graph construction (register-shaped, via ``Model.rw_classify`` — the
hook's contract is last-writer-wins state, models/base.py):

  * **Required ops** — forced (ok-completed) ops always linearize.  An
    optional (crashed) op is pulled in only when it is the UNIQUE
    writer of a value some required reader observed — then it must have
    linearized too (nobody else could have produced the value), fixed-
    pointed across chains of optional CASes.  All other optional ops
    are excluded: an edge through an op that might not linearize proves
    nothing.
  * **SO** (session order): consecutive required ops of one process, in
    open order — program order binds every op that linearizes.
  * **WR** (reads-from): reader r observed v (≠ the initial value) and
    exactly ONE op w in the whole encoded history writes v ⇒ w → r.
    Values written more than once contribute no WR edges (conservative,
    never unsound).
  * **RW** (anti-dependency): r reads v from unique writer w, and w' is
    a required writer whose order after w is KNOWN (same process as w,
    later open) ⇒ r → w' — r must precede the overwrite, else the only
    writer of v sits before w' and the state at r could not be v.
  * **Reads-of-initial**: r observed the initial value and NO op writes
    it ⇒ r → every required writer (any write destroys the initial
    value for good).

Soundness (doc/checker-design.md §15): each edge u → v holds in every
legal sequential execution of the required ops, and any witness the
sequential rung's kernel accepts IS such an execution — so a cycle
implies the rung kernel must answer INVALID too (composed verdicts
stay exact; the cheap certifier only ever certifies VALID, this tier
only ever refutes).  At the *session* rung the implemented guarantee
(monotonic reads + read-your-writes) does NOT imply full session order
— a monotonic-writes violation can pass the rung — so there the cycle
is attached as an ``sc-refuted`` annotation instead of a verdict: exact
evidence the history is not sequentially consistent even though the
weaker rung honestly holds (the sharper-than-relaxation acceptance
row, pinned in tests/test_cycle.py).

Execution: adjacency matrices batch across rows (pow2-bucketed node
counts, zero-padded rows), and the transitive closure is the batched
int32 boolean-matmul squaring kernel of ops/kernel_ir.make_cycle_closure
— free where matmul is free.  Off-TPU the same adjacency runs a host
DFS instead (verdict-identical; the PLATFORM_ROUTE idiom —
``JGRAFT_CYCLE_KERNEL`` forces either arm for tests/ablation).
"""

from __future__ import annotations

import functools
import os
import time
from typing import List, Optional, Sequence

import numpy as np

from ..history.packing import EV_FORCE, EV_OPEN, EncodedHistory
from ..ops.kernel_ir import (CYCLE_MAX_NODES, CYCLE_MAX_NODES_TILED,
                             CYCLE_TILE, cycle_closure_tile,
                             cycle_closure_tiles)
from ..platform import env_int


def cycle_tier_on() -> bool:
    """Whether the exact cycle tier runs at the weak rungs
    (JGRAFT_CYCLE_TIER=0 disables — the ablation arm; verdicts must be
    identical either way at the sequential rung, pinned by tests)."""
    return env_int("JGRAFT_CYCLE_TIER", 1, minimum=0) != 0


def cycle_tile() -> int:
    """Tile edge for the blocked closure kernel (JGRAFT_CYCLE_TILE,
    default ops/kernel_ir.CYCLE_TILE; 0 disables the tiled path — the
    ablation arm that reproduces the 512-cap tier, including its lower
    default node cap). Routing only: every arm is verdict-identical."""
    return env_int("JGRAFT_CYCLE_TILE", CYCLE_TILE, minimum=0)


def cycle_max_ops() -> int:
    """Per-row node cap (JGRAFT_CYCLE_MAX_OPS): rows whose required-op
    graph is bigger skip the tier — the kernel ladder still decides
    them, so the cap only moves work, never answers (and since ISSUE
    19 the skip leaves a trace: the cycle-skipped-size annotation +
    counter). Default is the blocked-closure cap (CYCLE_MAX_NODES_TILED
    = 4096) when the tiled kernel is enabled, the monolithic
    CYCLE_MAX_NODES = 512 when JGRAFT_CYCLE_TILE=0."""
    cap = CYCLE_MAX_NODES_TILED if cycle_tile() > 0 else CYCLE_MAX_NODES
    return env_int("JGRAFT_CYCLE_MAX_OPS", cap, minimum=1)


def _condense_env() -> Optional[bool]:
    """JGRAFT_CYCLE_CONDENSE force: True/False when set, None when the
    arm is left to the measured per-bucket choice (default: condense —
    the host Tarjan pre-pass is O(V+E) and decides plain cyclicity
    outright). =0 is the ablation arm reproducing the pre-ISSUE-19
    direct path bit for bit."""
    if os.environ.get("JGRAFT_CYCLE_CONDENSE") is None:
        return None
    return env_int("JGRAFT_CYCLE_CONDENSE", 1, minimum=0) != 0


def _use_kernel() -> bool:
    """Closure-kernel routing: the batched matmul pass where matmul is
    effectively free (TPU), the O(V+E) host DFS everywhere else — same
    measured-routing stance as PLATFORM_ROUTE_MIN_CELLS.
    JGRAFT_CYCLE_KERNEL=1/0 forces the arm (tests, ablation)."""
    forced = os.environ.get("JGRAFT_CYCLE_KERNEL")
    if forced is not None:
        return forced == "1"
    import jax

    return jax.default_backend() == "tpu"


# ------------------------------------------------------ graph building


def build_sc_graph(enc: EncodedHistory, model,
                   want_planes: bool = False) -> Optional[dict]:
    """Dependency graph of one encoded history, or None when the model
    cannot classify an op / the encoding has no per-event process ids.
    Returns {"n", "adj" ([n, n] uint8), "op_index" (node → original
    history op index)} — or, when the required-op count exceeds the
    cap, the skip marker {"skipped-nodes": count} so callers can stamp
    the previously-silent size skip (``"adj" in g`` distinguishes).

    With ``want_planes`` the result also carries ``"planes"``: the
    edge-class-labeled adjacency submatrices the transactional anomaly
    rung (checker/anomaly.py) closes over — ``po`` (session order),
    ``wr`` (reads-from), ``ww`` (write-version order: a reads-from
    edge into an op that itself writes — the reader installs the
    successor version, so the writers are version-ordered), ``rw``
    (anti-dependency + reads-of-initial).  adj is exactly the union of
    the planes; plane extraction never adds or drops an edge."""
    classify = getattr(model, "rw_classify", None)
    if classify is None or enc.proc is None or enc.n_events == 0:
        return None
    events = enc.events
    ops: List[tuple] = []   # (f, a, b, pid, hist_index)
    forced: List[bool] = []
    active: dict = {}
    for pos in range(enc.n_events):
        et, slot = int(events[pos, 0]), int(events[pos, 1])
        if et == EV_OPEN:
            active[slot] = len(ops)
            ops.append((int(events[pos, 2]), int(events[pos, 3]),
                        int(events[pos, 4]), int(enc.proc[pos]),
                        int(enc.op_index[pos])))
            forced.append(False)
        elif et == EV_FORCE:
            forced[active.pop(slot)] = True
    cls: List[tuple] = []
    for f, a, b, _pid, _hi in ops:
        c = classify(f, a, b)
        if c is None:
            return None  # one unclassifiable op poisons every edge
        cls.append(c)

    def read_of(k):
        c = cls[k]
        return c[1] if c[0] in ("r", "rw") else None

    def write_of(k):
        c = cls[k]
        return c[2] if c[0] == "rw" else (c[1] if c[0] == "w" else None)

    initial = model.init_state()
    writers: dict = {}
    for k in range(len(ops)):
        wv = write_of(k)
        if wv is not None:
            writers.setdefault(wv, []).append(k)

    # required = forced ∪ (unique writers of required-observed values),
    # to a fixpoint across optional CAS chains
    required = {k for k in range(len(ops)) if forced[k]}
    wr_edges = set()
    frontier = list(required)
    while frontier:
        nxt = []
        for r in frontier:
            rv = read_of(r)
            if rv is None or rv == initial:
                continue
            ws = writers.get(rv, [])
            if len(ws) == 1 and ws[0] != r:
                w = ws[0]
                wr_edges.add((w, r))
                if w not in required:
                    required.add(w)
                    nxt.append(w)
        frontier = nxt
    if len(required) > cycle_max_ops():
        return {"skipped-nodes": len(required)}

    order = sorted(required)               # open order
    node = {k: i for i, k in enumerate(order)}
    n = len(order)
    adj = np.zeros((n, n), dtype=np.uint8)
    planes = {c: np.zeros((n, n), dtype=np.uint8)
              for c in ("po", "ww", "wr", "rw")} if want_planes else None

    def edge(cls_name, u, v):
        adj[u, v] = 1
        if planes is not None:
            planes[cls_name][u, v] = 1

    # SO: consecutive required ops per process
    last_of: dict = {}
    for k in order:
        pid = ops[k][3]
        if pid in last_of:
            edge("po", node[last_of[pid]], node[k])
        last_of[pid] = k
    req_writers = [k for k in order if write_of(k) is not None]
    for w, r in wr_edges:
        edge("wr", node[w], node[r])
        if cls[r][0] == "rw":
            # the reader writes too (CAS-shaped): it installs the
            # version right after w's — a known write-order pair
            edge("ww", node[w], node[r])
        # RW: r must precede every overwrite whose order after w is
        # known (same process as w, opened later)
        for w2 in req_writers:
            if w2 != w and w2 != r and ops[w2][3] == ops[w][3] \
                    and w2 > w:
                edge("rw", node[r], node[w2])
    # reads-of-initial: no op writes the initial value ⇒ the reader
    # precedes every required writer
    if not writers.get(initial):
        for r in order:
            if read_of(r) == initial:
                for w2 in req_writers:
                    if w2 != r:
                        edge("rw", node[r], node[w2])
    np.fill_diagonal(adj, 0)
    out = {"n": n, "adj": adj,
           "op_index": [ops[k][4] for k in order]}
    if planes is not None:
        for p in planes.values():
            np.fill_diagonal(p, 0)
        out["planes"] = planes
    return out


# ------------------------------------------------------ cycle detection


def host_has_cycle(adj: np.ndarray) -> bool:
    """Iterative 3-color DFS over a dense adjacency matrix — the
    NetworkX-free host oracle the closure kernel is differentially
    pinned against (and the off-TPU production arm)."""
    n = int(adj.shape[0])
    color = np.zeros(n, dtype=np.int8)  # 0 white, 1 gray, 2 black
    succ = [np.flatnonzero(adj[i]) for i in range(n)]
    for root in range(n):
        if color[root]:
            continue
        stack = [(root, 0)]
        color[root] = 1
        while stack:
            v, j = stack[-1]
            if j < len(succ[v]):
                stack[-1] = (v, j + 1)
                w = int(succ[v][j])
                if color[w] == 1:
                    return True
                if color[w] == 0:
                    color[w] = 1
                    stack.append((w, 0))
            else:
                color[v] = 2
                stack.pop()
    return False


def cycle_witness(adj: np.ndarray) -> Optional[List[int]]:
    """One concrete cycle (node list, closed implicitly) from a cyclic
    adjacency matrix: shortest cycle through the first node that can
    reach itself (BFS) — small, checkable evidence for the result
    record."""
    n = int(adj.shape[0])
    for start in range(n):
        # BFS from start's successors back to start
        prev = np.full(n, -1, dtype=np.int64)
        q = []
        for s in np.flatnonzero(adj[start]):
            prev[int(s)] = start
            q.append(int(s))
        qi = 0
        while qi < len(q):
            v = q[qi]
            qi += 1
            if adj[v, start]:
                # path start → ... → v (→ start implicitly)
                path = [v]
                while path[-1] != start:
                    path.append(int(prev[path[-1]]))
                path.reverse()
                return path
            for w in np.flatnonzero(adj[v]):
                w = int(w)
                if prev[w] < 0:
                    prev[w] = v
                    q.append(w)
    return None


def tarjan_scc(adj: np.ndarray) -> List[List[int]]:
    """Strongly connected components of a dense adjacency matrix —
    host ITERATIVE Tarjan (explicit work stack; the required-op graphs
    now reach 4096 nodes, far past Python's recursion limit).
    Components come out in reverse topological order of the condensed
    DAG.  This is the condensation pre-pass oracle: a component of
    size ≥ 2 contains a cycle (two mutually-reachable nodes), and any
    dependency cycle lies entirely inside one component — so
    "non-trivial SCC exists" ⇔ "cycle exists", with no kernel launch
    (doc/checker-design.md §21)."""
    n = int(adj.shape[0])
    succ = [np.flatnonzero(adj[i]).tolist() for i in range(n)]
    index = [-1] * n
    low = [0] * n
    on_stack = [False] * n
    stack: List[int] = []
    comps: List[List[int]] = []
    counter = 0
    for root in range(n):
        if index[root] != -1:
            continue
        work = [(root, 0)]
        while work:
            v, pi = work[-1]
            if pi == 0:
                index[v] = low[v] = counter
                counter += 1
                stack.append(v)
                on_stack[v] = True
            descended = False
            for i in range(pi, len(succ[v])):
                w = succ[v][i]
                if index[w] == -1:
                    work[-1] = (v, i + 1)
                    work.append((w, 0))
                    descended = True
                    break
                if on_stack[w]:
                    low[v] = min(low[v], index[w])
            if descended:
                continue
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack[w] = False
                    comp.append(w)
                    if w == v:
                        break
                comps.append(comp)
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[v])
    return comps


@functools.lru_cache(maxsize=None)
def _closure_kernel(n_nodes: int):
    from ..ops.kernel_ir import make_cycle_closure

    return make_cycle_closure(n_nodes)


@functools.lru_cache(maxsize=None)
def _closure_kernel_tiled(n_nodes: int, tile: int):
    from ..ops.kernel_ir import make_cycle_closure_tiled

    return make_cycle_closure_tiled(n_nodes, tile)


def closure_fn(n_bucket: int):
    """The closure kernel for a node bucket, with its tile-program
    count for the cycle_tiles_run counter: the monolithic [N, N]
    squaring up to CYCLE_MAX_NODES (one "tile"), the blocked
    Floyd–Warshall kernel above it (when JGRAFT_CYCLE_TILE > 0), None
    past the enabled cap — callers fall back to the host DFS."""
    if n_bucket <= CYCLE_MAX_NODES:
        return _closure_kernel(n_bucket), 1
    t = cycle_tile()
    if t <= 0 or n_bucket > CYCLE_MAX_NODES_TILED:
        return None, 0
    t = cycle_closure_tile(n_bucket, t)
    return (_closure_kernel_tiled(n_bucket, t),
            cycle_closure_tiles(n_bucket, t))


def _condense_detect(g: dict) -> Optional[dict]:
    """Condensation arm for one graph: Tarjan decides cyclicity
    outright — a non-trivial SCC is an immediate cycle verdict (the
    witness search runs inside that component only), no SCC ⇒ acyclic,
    and either way no kernel launches. Counters: nodes_post is the
    condensed-DAG size (number of components), scc_hits the number of
    non-trivial components."""
    from .schedule import note_cycle

    comps = tarjan_scc(g["adj"])
    nontrivial = [c for c in comps if len(c) >= 2]
    note_cycle(cycle_nodes_post=len(comps),
               cycle_scc_hits=len(nontrivial))
    if not nontrivial:
        return None
    comp = sorted(min(nontrivial, key=min))   # deterministic pick
    sub = g["adj"][np.ix_(comp, comp)]
    path = cycle_witness(sub) or []
    return {"cycle": [g["op_index"][comp[v]] for v in path],
            "nodes": g["n"]}


def _direct_flags(rows: List[tuple], N: int, use_kernel: bool) -> dict:
    """Direct arm over one bucket's graphs: batched closure launch or
    per-graph host DFS.  Returns {row index: has_cycle}."""
    from .schedule import note_cycle

    kfn = tiles = None
    if use_kernel:
        kfn, tiles = closure_fn(N)
    if kfn is not None:
        batch = np.zeros((len(rows), N, N), dtype=np.int32)
        for j, (_i, g) in enumerate(rows):
            batch[j, :g["n"], :g["n"]] = g["adj"]
        has, _closed = kfn(batch)
        has = np.asarray(has)  # lint: allow(host-sync)
        if tiles > 1:
            note_cycle(cycle_tiles_run=tiles)
        return {i: bool(has[j]) for j, (i, _g) in enumerate(rows)}
    return {i: host_has_cycle(g["adj"]) for i, g in rows}


def _bucket_arm(N: int, rows: List[tuple],
                kernel: Optional[bool]) -> str:
    """Arm choice for one node bucket: "condense" | "kernel" | "dfs".

    Precedence: a JGRAFT_CYCLE_CONDENSE force wins; otherwise forcing
    the direct arm explicitly (the `kernel` parameter or
    JGRAFT_CYCLE_KERNEL) is a request to EXERCISE that arm — tests and
    ablations pin the kernel/DFS differential through here, and the
    condensation pre-pass would shadow it.  With nothing forced the
    measured per-bucket arm applies (checker/autotune.py cycle-arm
    store, resolved on first contact once the bucket carries enough
    work to time honestly), defaulting to condensation.  Every arm is
    verdict-identical, so this is routing only — knobclass-proven."""
    forced_cond = _condense_env()
    env_kern = os.environ.get("JGRAFT_CYCLE_KERNEL")
    if forced_cond is True:
        return "condense"
    direct_kernel = kernel if kernel is not None else _use_kernel()
    if forced_cond is False or kernel is not None or env_kern is not None:
        return "kernel" if direct_kernel else "dfs"
    from . import autotune

    if autotune.autotune_on():
        sig = autotune.cycle_arm_sig(N)
        arm = autotune.cycle_arm_for(sig)
        if arm is None and N * N * len(rows) >= autotune.min_cells():
            arm = autotune.resolve_cycle_arm(
                sig, _arm_measures(N, rows, direct_kernel))
        if arm is not None:
            if arm == "kernel" and (not direct_kernel
                                    or closure_fn(N)[0] is None):
                arm = "dfs"
            return arm
    return "condense"


def _arm_measures(N: int, rows: List[tuple], allow_kernel: bool) -> dict:
    """Zero-arg wall-second measurements over the bucket's real graphs
    for the autotuner's interleaved resolve. The kernel arm is offered
    only where a kernel exists for the bucket (and the caller hasn't
    vetoed launches)."""
    def timed(fn):
        def run():
            t0 = time.perf_counter()
            fn()
            return time.perf_counter() - t0
        return run

    measures = {
        "condense": timed(lambda: [tarjan_scc(g["adj"])
                                   for _i, g in rows]),
        "dfs": timed(lambda: [host_has_cycle(g["adj"])
                              for _i, g in rows]),
    }
    if allow_kernel and closure_fn(N)[0] is not None:
        measures["kernel"] = timed(
            lambda: _direct_flags(rows, N, use_kernel=True))
    return measures


def find_cycles(encs: Sequence[EncodedHistory], model,
                kernel: Optional[bool] = None
                ) -> List[Optional[dict]]:
    """Per row: None (no graph / acyclic), {"cycle": [history op
    indices...], "nodes": n} — an exact SC refutation witness — or
    {"skipped-size": n} when the required-op graph exceeds
    cycle_max_ops() (the previously-silent cap skip, now stamped and
    counted; callers test ``"cycle" in c``).  Graphs batch by
    pow2-bucketed node count; per bucket the arm is condensation
    (host Tarjan — the default), the batched closure kernel
    (monolithic ≤ 512 nodes, blocked Floyd–Warshall above), or the
    host DFS — forced by JGRAFT_CYCLE_CONDENSE / JGRAFT_CYCLE_KERNEL,
    measured per bucket otherwise (identical answers every way, pinned
    by tests).  `kernel` overrides the launch routing (False = no
    kernel even on TPU — graftd's device-degrade path must not
    launch)."""
    from ..history.packing import bucket_rows
    from .schedule import note_cycle

    out: List[Optional[dict]] = [None] * len(encs)
    built = []
    skipped = 0
    for i, enc in enumerate(encs):
        g = build_sc_graph(enc, model)
        if g is None:
            continue
        if "adj" not in g:
            out[i] = {"skipped-size": g["skipped-nodes"]}
            skipped += 1
        elif g["n"] >= 2:
            built.append((i, g))
    if skipped:
        note_cycle(cycle_size_skips=skipped)
    if not built:
        return out
    note_cycle(cycle_nodes_pre=sum(g["n"] for _i, g in built))
    by_bucket: dict = {}
    for i, g in built:
        by_bucket.setdefault(bucket_rows(g["n"], 4), []).append((i, g))
    for N, rows in by_bucket.items():
        arm = _bucket_arm(N, rows, kernel)
        if arm == "condense":
            for i, g in rows:
                out[i] = _condense_detect(g)
            continue
        flags = _direct_flags(rows, N, use_kernel=(arm == "kernel"))
        for i, g in rows:
            if flags.get(i):
                path = cycle_witness(g["adj"]) or []
                out[i] = {"cycle": [g["op_index"][v] for v in path],
                          "nodes": g["n"]}
    return out

"""Interval (bounds) counter checker — the sound, never-gives-up tier.

Jepsen's library ships `checker/counter` as THE stock counter checker:
every read must fall within [sum of definitely-applied deltas, sum of
possibly-applied deltas] over the read's own duration. It is weaker
than linearizability (order-blind) but SOUND in the direction that
matters: a bounds violation is a real consistency bug under any
linearization, and bounds-validity is the certificate jepsen itself
accepts for counters.

Here it backs the exact engines rather than replacing them
(workload/counter.py CounterChecker): the reference's hand-written
knossos CounterModel (counter.clj:100-127) — which our Counter model
mirrors — becomes infeasible at the canonical envelope (60-90 s,
concurrency 100 hell runs pile up thousands of crashed adds, so the
concurrency window dwarfs every frontier/DFS budget and the exact
verdict is UNKNOWN; the reference community's own stance is
"unfeasible to verify", doc/intro.md:35-41). Instead of stopping at
UNKNOWN, the run is decided at the interval tier and labeled with the
weaker certificate — strictly more evidence than the reference
produces at the same scale.

Negative deltas (the reference's decrement maps to a negated add,
counter.clj:56-59) mirror the bounds: a possibly-applied negative
delta lowers `lo`, a definite one raises... lowers `hi`. All widening
is monotone toward soundness: transient possibilities (e.g. a failed
add's window) stay inside open readers' extremes.
"""

from __future__ import annotations

from typing import Optional

from ..history.ops import FAIL, INFO, INVOKE, OK, History
from .base import Checker, UNKNOWN


def _signed_delta(op) -> Optional[int]:
    """Signed delta of an add-family op at INVOKE time, else None."""
    f = op.f
    if f in ("add", "add-and-get"):
        return int(op.value)
    if f in ("decr", "decr-and-get"):
        return -int(op.value)
    return None


def interval_check(history: History) -> dict:
    """Sound bounds check of a counter history.

    Walks ops in real-time order maintaining the possible value range
    [lo, hi]; each read (and each add-and-get's implied pre-state
    observation) must fall within the range's envelope OVER ITS OWN
    SPAN — checking against the instantaneous range at completion
    would false-flag a read that linearized before a concurrent add
    completed mid-span.
    """
    lo = hi = 0
    # process -> [lo_min, hi_max] envelope since the observer's invoke
    open_obs: dict = {}

    def widen() -> None:
        for env in open_obs.values():
            if lo < env[0]:
                env[0] = lo
            if hi > env[1]:
                env[1] = hi

    reads = 0
    for idx, op in enumerate(history):
        f = op.f
        is_obs = f in ("read", "get", "add-and-get", "decr-and-get")
        if op.type == INVOKE:
            sd = _signed_delta(op)
            if sd is not None:  # possibly applied from invocation on
                if sd > 0:
                    hi += sd
                else:
                    lo += sd
                widen()
            if is_obs:
                open_obs[op.process] = [lo, hi]
        elif op.type == OK:
            env = open_obs.pop(op.process, [lo, hi])
            if f in ("read", "get"):
                v = int(op.value)
                reads += 1
                if not env[0] <= v <= env[1]:
                    return {
                        "valid?": False,
                        "checker": "counter-interval",
                        "error": f"read {v} outside possible range "
                                 f"[{env[0]}, {env[1]}]",
                        "op-index": idx,
                    }
            elif f in ("add-and-get", "decr-and-get"):
                delta, new = op.value
                sd = delta if f == "add-and-get" else -delta
                pre = int(new) - sd
                reads += 1
                # Own delta already widened one side at invoke; the
                # pre-state bound is checked against the (thus slightly
                # wide) envelope — monotone toward soundness.
                if not env[0] <= pre <= env[1]:
                    return {
                        "valid?": False,
                        "checker": "counter-interval",
                        "error": f"add-and-get observed {new} (pre-state "
                                 f"{pre}) outside possible range "
                                 f"[{env[0]}, {env[1]}]",
                        "op-index": idx,
                    }
                # ...and the delta is now definite.
                if sd > 0:
                    lo += sd
                else:
                    hi += sd
                widen()
            if f in ("add", "decr"):
                sd = _signed_delta(op)
                if sd > 0:
                    lo += sd
                else:
                    hi += sd
                widen()
        elif op.type == FAIL:
            open_obs.pop(op.process, None)
            sd = _signed_delta(op)
            if sd is not None:  # definitely never applied: retract the
                if sd > 0:      # possibility (open envelopes keep the
                    hi -= sd    # transient — sound, wider)
                else:
                    lo -= sd
        elif op.type == INFO:
            # Crashed: may or may not have applied, forever — the
            # possibility stays in the range. Observers: no constraint.
            open_obs.pop(op.process, None)
    return {"valid?": True, "checker": "counter-interval",
            "reads-checked": reads, "final-range": [lo, hi]}


def decide_unknown_with_interval(exact_result: dict,
                                 history: History) -> dict:
    """Decide an exact-UNKNOWN counter verdict at the interval tier,
    labeling the weaker certificate and attaching the exhausted exact
    attempt. Shared by the live workload checker (CounterChecker) and
    the recorded-store re-check path so the two produce the same
    results.json shape."""
    b = interval_check(history)
    b["certificate"] = "interval"
    b["exact"] = {k: v for k, v in exact_result.items() if k != "valid?"}
    b["note"] = ("exact engines exhausted (window beyond budget); "
                 "verdict decided at jepsen checker/counter interval "
                 "semantics")
    return b


class CounterChecker(Checker):
    """Exact linearizability first; the interval tier decides UNKNOWNs.

    The composed verdict is never UNKNOWN: exact verdicts pass through
    untouched; an exact-UNKNOWN (window beyond every engine's budget —
    the canonical-envelope counter shape) is decided by the bounds
    check and labeled ``certificate: interval`` so the weaker evidence
    is visible in results.json.
    """

    def __init__(self, exact: Checker):
        self.exact = exact

    def check(self, test, history, opts=None) -> dict:
        r = self.exact.check(test, history, opts)
        if r.get("valid?") is not UNKNOWN:
            return r
        if not isinstance(history, History):
            history = History(history)
        return decide_unknown_with_interval(r, history.client_ops())

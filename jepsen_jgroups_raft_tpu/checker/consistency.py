"""Weaker-consistency rungs: relaxed-precedence scans over the packed
event tensors.

The ladder below full linearizability (ROADMAP item 4) is built from ONE
observation: the packed event stream (history/packing.py) encodes ALL
real-time precedence through FORCE placement — an op must linearize
between its OPEN and its FORCE. A weaker consistency rung is therefore a
*stream transform*, not a new engine: defer each op's FORCE along the
axis the rung cares about and re-run the identical frontier machinery
(dense/mask/sort kernels, macro compaction, chunked eviction, autotune
bucketing, graftd coalescing — all of it consumes `EncodedHistory` and
applies unchanged).

Rungs (strong → weak), by FORCE placement:

  ``linearizable``  — FORCE at the op's real-time completion (the
                      untouched encoding).
  ``sequential``    — FORCE deferred to just before the same process's
                      NEXT op opens (or end of stream): cross-process
                      real-time edges are dropped, per-process program
                      order is kept.
  ``session``       — (monotonic-reads tier) FORCE deferred to just
                      before the same process's next *read* opens
                      (``Model.readonly_fcodes``), else end of stream:
                      only reads must observe their session's earlier
                      ops.

Soundness (doc/checker-design.md §12 for the full argument):

  * Monotone relaxation: every rung only moves FORCEs later (clamped to
    ``max(original, deferred)``), so any linearization witness survives
    each step down the ladder — a history passing linearizability
    passes every weaker rung, and a FAIL at a weak rung certifies
    non-linearizability (the rung-ordering property tests pin both).
  * Positive certification: a ``sequential`` witness linearizes each op
    before its process's next op opens, hence before that op — the
    witness respects program order, so a PASS certifies sequential
    consistency. (The rung may be stricter than full SC: stream order
    still carries the cross-process edges the interval encoding cannot
    drop — exact SC checking is NP-hard and out of scope; this is the
    tractable interval-order relaxation.) A ``session`` PASS certifies
    that each read observes all earlier same-session ops — monotonic
    reads + read-your-writes.

Why the rungs are CHEAPER: a weaker rung admits more witnesses, so the
value-guided bounded-backtrack certifier below (an O(events · window)
host scan with a fixed flip budget, no kernel launch) succeeds on the
overwhelming majority of valid histories — the measured A/B win
(scripts/ab_cheap_tier.py). Rows it cannot certify fall through to the
ordinary kernel ladder on the relaxed stream; the certifier never
*refutes*, so its answers are sound by construction (the committed
order IS a witness). Soundness + tier ordering live in
doc/checker-design.md §15.
"""

from __future__ import annotations

import heapq
from collections import deque
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..history.packing import EV_FORCE, EV_OPEN, EncodedHistory
from ..platform import env_int

#: Rung names, strongest first. Index = position in the ladder.
CONSISTENCY_LEVELS = ("linearizable", "sequential", "session")

_ALIASES = {
    "lin": "linearizable",
    "linearizability": "linearizable",
    "seq": "sequential",
    "monotonic-reads": "session",
    "monotonic": "session",
}


def normalize_consistency(name: Optional[str]) -> str:
    """Canonical rung name (aliases accepted); ValueError on unknowns —
    the service maps that to a 400 at admission, never into the queue."""
    if name is None:
        return "linearizable"
    n = _ALIASES.get(str(name).strip().lower(), str(name).strip().lower())
    if n not in CONSISTENCY_LEVELS:
        raise ValueError(
            f"unknown consistency {name!r}; valid: "
            f"{CONSISTENCY_LEVELS} (aliases: {sorted(_ALIASES)})")
    return n


def rung_index(name: str) -> int:
    return CONSISTENCY_LEVELS.index(normalize_consistency(name))


def greedy_on() -> bool:
    """Whether the greedy witness certifier runs before the kernel pass
    on weaker rungs. ``JGRAFT_GREEDY_CERTIFY=0`` disables it — the
    ablation arm (rung verdicts must be identical either way, pinned by
    tests) and the A/B denominator."""
    return env_int("JGRAFT_GREEDY_CERTIFY", 1, minimum=0) != 0


#: Default BASE flip budget for the bounded-backtrack certifier:
#: enough to untangle the mutator ambiguity that defeats the pure
#: greedy scan on the register/cas family (measured: 98/100 seeded
#: 200-op register histories certify under 64 flips where PR-9 greedy
#: managed 9/100), small enough that an adversarial history cannot
#: turn the cheap tier into a search engine — undecided rows take the
#: exact kernel ladder. The EFFECTIVE per-row budget scales with
#: stream length (`_effective_budget`): wrong turns accumulate
#: linearly with ops, so a flat budget silently starved long histories
#: (1000-op register decided fraction 0.67 flat vs 1.0 scaled,
#: measured at ~equal wall — undecided rows are the expensive ones).
DEFAULT_BACKTRACK_BUDGET = 64

#: Events per base-budget unit in the length scaling.
_BUDGET_SCALE_EVENTS = 256

#: Most-recent choice points kept restorable. Dropping the oldest when
#: the stack outgrows this bounds certifier memory to
#: O(cap · ops/word) regardless of history length; a search that needs
#: deeper backtracking returns undecided (never wrong).
_BACKTRACK_STACK_CAP = 128


def greedy_backtrack_budget() -> int:
    """Resolved BASE flip budget (JGRAFT_GREEDY_BACKTRACK; 0 restores
    the PR-9 no-backtrack greedy behavior — the ablation arm)."""
    return env_int("JGRAFT_GREEDY_BACKTRACK", DEFAULT_BACKTRACK_BUDGET,
                   minimum=0)


class _AbortBudget(Exception):
    """Internal: the certifier's step-count abort budget ran out
    (ISSUE 14). Converted to an undecided answer — never a verdict."""


def _effective_budget(base: int, n_events: int) -> int:
    """Per-row budget: the base, scaled linearly past
    `_BUDGET_SCALE_EVENTS` events (64 at ≤256 events, ~448 at a
    2000-event 1000-op register history)."""
    return base * max(1, n_events // _BUDGET_SCALE_EVENTS)


# ----------------------------------------------------- stream relaxation


def relax_encoded(enc: EncodedHistory, model,
                  consistency: str) -> EncodedHistory:
    """Re-encode one packed history with the rung's relaxed FORCE
    placement (module docstring). Pure host transform on the packed
    tensors; slot assignment is re-run so the relaxed stream is a
    first-class `EncodedHistory` every kernel family accepts.

    An encoding without per-event process ids (`proc is None` — hand
    built, or loaded from an older artifact) cannot be relaxed
    per-process; it is returned UNCHANGED, which is conservative and
    sound in both directions (the rung is then exactly linearizability
    for that row: a pass still implies the weaker guarantee, a fail
    still certifies non-linearizability)."""
    consistency = normalize_consistency(consistency)
    if consistency == "linearizable" or enc.n_events == 0:
        return enc
    proc = enc.proc
    if proc is None or len(proc) != enc.n_events:
        return enc
    events = enc.events
    op_index = enc.op_index
    readonly = frozenset(getattr(model, "readonly_fcodes", ()) or ())

    # -- decode the stream back into ops -------------------------------
    # op record: [open_pos, f, a, b, open_idx, pid, force_pos|-1,
    #             force_idx] (force_idx = the completion row's history
    #             index — FORCE rows must keep reporting it so rung
    #             counterexamples point at the completion, like the
    #             original encoding's op_index convention).
    ops: List[list] = []
    active: dict = {}          # slot -> op record index
    per_proc: dict = {}        # pid -> [op record index...] in open order
    for pos in range(enc.n_events):
        et = int(events[pos, 0])
        slot = int(events[pos, 1])
        if et == EV_OPEN:
            k = len(ops)
            ops.append([pos, int(events[pos, 2]), int(events[pos, 3]),
                        int(events[pos, 4]), int(op_index[pos]),
                        int(proc[pos]), -1, -1])
            active[slot] = k
            per_proc.setdefault(int(proc[pos]), []).append(k)
        elif et == EV_FORCE:
            k = active.pop(slot)
            ops[k][6] = pos
            ops[k][7] = int(op_index[pos])

    # -- per-process deferral targets ----------------------------------
    END = enc.n_events
    anchor: List[Optional[int]] = [None] * len(ops)  # forced ops only
    for pid, ks in per_proc.items():
        for j, k in enumerate(ks):
            if ops[k][6] < 0:
                continue  # optional op: never forced, nothing to move
            later = ks[j + 1:]
            if consistency == "sequential":
                cand = ops[later[0]][0] if later else END
            else:  # session: next same-process READ open
                cand = END
                for k2 in later:
                    if ops[k2][1] in readonly:
                        cand = ops[k2][0]
                        break
            # Monotone-relaxation clamp: never move a FORCE earlier
            # than its real-time position (ill-formed inputs included).
            anchor[k] = cand if cand > ops[k][6] else ops[k][6]

    # -- rebuild: opens at their positions, forces just before their
    # anchor opens (END = past everything); ties among deferred forces
    # keep original completion order. kind 0 (force) sorts before kind 1
    # (open) at the same anchor, which is exactly "just before".
    items = []
    for k, o in enumerate(ops):
        items.append((o[0], 1, k, EV_OPEN))
        if o[6] >= 0:
            items.append((anchor[k], 0, o[6], EV_FORCE, k))
    items.sort(key=lambda it: (it[0], it[1], it[2]))

    n_ev = len(items)
    out = np.zeros((n_ev, 5), dtype=np.int32)
    out_idx = np.empty(n_ev, dtype=np.int32)
    out_proc = np.empty(n_ev, dtype=np.int32)
    slot_of: dict = {}
    free: List[int] = []
    next_slot = 0
    for j, it in enumerate(items):
        if it[3] == EV_OPEN:
            k = it[2]
            if free:
                s = heapq.heappop(free)
            else:
                s = next_slot
                next_slot += 1
            slot_of[k] = s
            out[j] = (EV_OPEN, s, ops[k][1], ops[k][2], ops[k][3])
            out_idx[j] = ops[k][4]
        else:
            k = it[4]
            s = slot_of[k]
            out[j] = (EV_FORCE, s, 0, 0, 0)
            heapq.heappush(free, s)
            out_idx[j] = ops[k][7]
        out_proc[j] = ops[k][5]
    return EncodedHistory(events=out, op_index=out_idx,
                          n_slots=next_slot, n_ops=len(ops),
                          proc=out_proc)


# ----------------------------------- value-guided backtracking certifier


def _value_guide_masks(model, ops, forced):
    """Per-op (enable_mask, observe_mask) bitmasks over the observed
    value domain — GSet's membership-mask encoding trick applied to the
    certifier's choice ordering: `enable_mask[k] & observe_mask[e]`
    answers "can committing k expose a state e observes?" in one AND.
    None when the model lacks the enable/observe hooks, answers None
    for some op, or the domain outgrows the word — the step-lookahead
    fallback then orders candidates instead (exact, just slower)."""
    from ..models.base import EncodedOp

    if not (hasattr(model, "enable_values")
            and hasattr(model, "observe_values")):
        return None
    dom: dict = {}
    em = [0] * len(ops)
    om = [0] * len(ops)
    for k, (f, a, b) in enumerate(ops):
        eo = EncodedOp(f, a, b, forced[k])
        evs = model.enable_values(eo)
        ovs = model.observe_values(eo)
        if evs is None or ovs is None:
            return None
        for vals, masks in ((evs, em), (ovs, om)):
            for v in vals:
                if v not in dom:
                    if len(dom) >= 63:
                        return None
                    dom[v] = len(dom)
                masks[k] |= 1 << dom[v]
    return em, om


def certify_encoded(enc: EncodedHistory, model,
                    budget: Optional[int] = None,
                    max_steps: Optional[int] = None
                    ) -> Tuple[bool, Optional[str], int]:
    """Witness construction on an encoded stream, with value-guided
    bounded backtracking (the ISSUE-13 widening of PR 9's one-pass
    greedy). Returns ``(certified, tier, flips)`` — tier "greedy" when
    the first-choice path succeeded, "backtrack" when recovering from
    ``flips`` wrong turns did, None when undecided.

    Commit rules (the PR-9 rules, now restartable):

      * EAGER observations: a pending READ-ONLY op (an opcode in
        `readonly_fcodes` — never mutates at ANY state) that is legal
        NOW commits immediately — provably lossless: if any witness
        places a read-only op elsewhere, moving it to the current legal
        point yields another witness (the op preserves state), so eager
        commits never foreclose anything and are NOT choice points.
      * LAZY mutations: a state-changing op commits only at its own
        FORCE, or when a forced op needs its effect.
      * CHOICE POINTS: every FORCE of a mutator is a decision — commit
        it directly (when legal), or commit some older pending op first
        and re-try. The pure greedy took the first option and aborted
        on any dead end; this certifier snapshots (pos, state, done)
        per decision and, on a dead end, restores the most recent
        snapshot with untried options — up to ``budget`` flips
        (`JGRAFT_GREEDY_BACKTRACK`), after which it returns undecided.
      * VALUE-GUIDED ordering: candidate commits are ranked by whether
        they can expose a state the blocked op observes (the
        enable/observe bitmask intersection above, confirmed by a
        1-step lookahead; pure lookahead for models without the hooks
        — this is what places a crashed queue landmine ENQ_ANY/DEQ_ANY
        lazily at the first state where it unblocks a forced op), then
        will-be-forced ops before optional crashed ops (known outcomes
        before poison), then open order.

    Soundness is unchanged from PR 9: True is returned only when a
    complete legal witness respecting every [OPEN, FORCE] interval was
    built, so True is a sound VALID for whatever rung produced the
    stream; False/undecided NEVER refutes — callers fall through to the
    exact kernel ladder (doc/checker-design.md §15).

    ``max_steps`` (ISSUE 14): an ABORT budget on total `model.step`
    calls. The flip budget bounds backtracking but not the scan's raw
    candidate-enumeration work, so a hopeless row on the linearizable
    fast path could otherwise cost an unbounded fraction of its kernel
    wall; past the budget the row returns undecided (never wrong — the
    kernels answer). None/0 = unbounded, today's exact behavior; the
    lin fast path passes a length-scaled budget
    (JGRAFT_LIN_FASTPATH_ABORT · events, checker/linearizable.py).

    NOTE: `StreamingCertifier` below is this scan's resumable twin —
    commit rules and candidate ordering are mirrored BY HAND (see its
    lock-step contract note for why they are not unified)."""
    state = model.init_state()
    step = model.step
    if max_steps is not None and max_steps > 0:
        raw_step, left = step, [int(max_steps)]

        def step(s, f, a, b):
            left[0] -= 1
            if left[0] < 0:
                raise _AbortBudget()
            return raw_step(s, f, a, b)
    readonly = frozenset(getattr(model, "readonly_fcodes", ()) or ())
    if budget is None:
        budget = _effective_budget(greedy_backtrack_budget(),
                                   enc.n_events)
    events = enc.events.tolist()
    n_ev = len(events)

    # -- pre-decode: flat op table + per-event (etype, op id) ----------
    ops: List[tuple] = []          # (f, a, b) per op, in open order
    op_forced: List[bool] = []     # will this op's slot see a FORCE?
    ev_ops: List[tuple] = []       # (etype, op id) per event position
    active: dict = {}
    for pos in range(n_ev):
        et, slot = events[pos][0], events[pos][1]
        if et == EV_OPEN:
            k = len(ops)
            ops.append((events[pos][2], events[pos][3], events[pos][4]))
            op_forced.append(False)
            active[slot] = k
            ev_ops.append((EV_OPEN, k))
        elif et == EV_FORCE:
            k = active.pop(slot)
            op_forced[k] = True
            ev_ops.append((EV_FORCE, k))
        else:
            ev_ops.append((0, -1))
    opened_by = [0] * (n_ev + 1)   # #ops opened among events[:pos]
    for pos in range(n_ev):
        opened_by[pos + 1] = opened_by[pos] + (
            1 if ev_ops[pos][0] == EV_OPEN else 0)
    guide = _value_guide_masks(model, ops, op_forced)

    def sweep(state, done, pending):
        # One pass suffices: read-only commits leave the state (the
        # only legality input) unchanged.
        for k in pending:
            if not (done >> k) & 1 and ops[k][0] in readonly \
                    and step(state, *ops[k])[1]:
                done |= 1 << k
        return done

    def candidates(state, done, pending, e):
        """Ordered commit options at op e's FORCE. None = commit e
        directly (listed first when legal — the greedy choice);
        otherwise an older pending op id, value-guided order."""
        te = ops[e]
        s_e, legal_e = step(state, *te)
        out = []
        if legal_e:
            out.append((-1, 0, 0, -1, None))
        for k in pending:
            if (done >> k) & 1 or k == e:
                continue
            s2, legal = step(state, *ops[k])
            if not legal:
                continue
            if guide is not None and not (guide[0][k] & guide[1][e]):
                enables = 1  # mask proves k exposes nothing e observes
            else:
                enables = 0 if step(s2, *te)[1] else 1
            out.append((0, enables, 0 if op_forced[k] else 1, k, k))
        out.sort(key=lambda t: t[:4])
        return [t[4] for t in out]

    flips = 0
    # choice points: [pos, state, done, candidates|None (lazy), next].
    # A None candidate list is computed only on first restore — the
    # never-backtracked common path (every valid unambiguous row) pays
    # one direct step() per FORCE exactly like the PR-9 scan, not a
    # full candidate enumeration.
    stack: deque = deque(maxlen=_BACKTRACK_STACK_CAP)
    pending: List[int] = []
    pos, done = 0, 0
    try:
        while pos < n_ev:
            et, k = ev_ops[pos]
            if et == EV_OPEN:
                f, a, b = ops[k]
                # Eager-commit at open when read-only and already legal
                # (the rest of `pending` was swept at this same state).
                if f in readonly and step(state, f, a, b)[1]:
                    done |= 1 << k
                else:
                    pending.append(k)
                pos += 1
                continue
            if et != EV_FORCE or (done >> k) & 1:
                pos += 1
                continue
            s_k, legal_k = step(state, *ops[k])
            choice = None
            if legal_k:
                # greedy direct commit; alternatives resolve lazily
                if budget > 0 and any(not (done >> o) & 1
                                      for o in pending):
                    stack.append([pos, state, done, None, 1])
            else:
                cands = candidates(state, done, pending, k)
                if cands:
                    if len(cands) > 1 and budget > 0:
                        stack.append([pos, state, done, cands, 1])
                    choice = cands[0]
                else:
                    # dead end: restore the most recent choice point
                    # with an untried option (one restore = one flip)
                    while stack:
                        cp = stack[-1]
                        if cp[3] is None:  # lazy: enumerate at its state
                            kc = ev_ops[cp[0]][1]
                            pc = [o for o in range(opened_by[cp[0]])
                                  if not (cp[2] >> o) & 1]
                            cp[3] = candidates(cp[1], cp[2], pc, kc)
                        if cp[4] < len(cp[3]):
                            flips += 1
                            if flips > budget:
                                return False, None, flips
                            pos, state, done = cp[0], cp[1], cp[2]
                            choice = cp[3][cp[4]]
                            cp[4] += 1
                            k = ev_ops[pos][1]
                            pending = [o for o in range(opened_by[pos])
                                       if not (done >> o) & 1]
                            break
                        stack.pop()
                    else:
                        return False, None, flips  # undecided — kernels
            commit = k if choice is None else choice
            state = step(state, *ops[commit])[0]
            done = sweep(state, done | (1 << commit), pending)
            if choice is None:
                pos += 1
            # else: stay at pos — re-evaluate k's FORCE at the new state
            pending = [o for o in pending if not (done >> o) & 1]
    except _AbortBudget:
        return False, None, flips  # abort budget spent — undecided
    return True, ("greedy" if flips == 0 else "backtrack"), flips


def greedy_certify(enc: EncodedHistory, model,
                   budget: Optional[int] = None) -> bool:
    """Boolean view of :func:`certify_encoded` (the historical PR-9
    entry; True = sound VALID witness built, False = undecided)."""
    return certify_encoded(enc, model, budget=budget)[0]


# ------------------------------------------------- resumable certifier


class StreamingCertifier:
    """Incremental twin of :func:`certify_encoded` for streaming
    sessions (ISSUE 14 tentpole (3)). `feed` consumes settled event
    suffixes (the `IncrementalEncoder` output) and advances the same
    witness construction, keeping the certifier's carry — (state,
    done-set, pending, backtrack stack) — BETWEEN appends, so a
    long-lived session's per-append cost is O(segment) instead of the
    per-append full restart's O(history). The carry lives next to
    `CarriedScan`'s ``{inner, left}`` kernel carry and, like it, is
    never journaled: a crash resume replays the journaled segments
    through the identical deterministic pipeline, so the rebuilt
    certifier state is field-for-field identical to the uninterrupted
    session's (pinned by tests/test_stream.py).

    Differences from the one-shot scan, and why they are sound:

      * ``op_forced`` is learned as FORCEs settle (the one-shot
        pre-scans the whole stream). It only RANKS candidates
        (will-be-forced before optional), so a late-learned force can
        cost flips, never a wrong answer; the value-guide masks are
        recomputed when an op's FORCE settles for the same reason.
      * `certified` mid-stream means the settled PREFIX has a complete
        witness — exactly what the per-append restart certified — and
        the final `feed` (after the encoder's end-of-history settle)
        certifies the whole history.
      * Once undecided (flip budget spent, no restorable choice point)
        the certifier is PERMANENTLY dead and the caller's kernel
        carry takes over — it never un-decides, matching the one-shot
        contract that undecided falls to the exact ladder.

    The flip budget is length-scaled like the one-shot's
    (`_effective_budget` over TOTAL settled events, re-resolved per
    feed, so a growing session earns budget as it grows).

    LOCK-STEP CONTRACT with :func:`certify_encoded`: `_sweep` /
    `_candidates` / `_scan` mirror the one-shot's commit rules and
    candidate ordering on purpose — the one-shot stays a hand-tuned
    closure loop because it is the MEASURED hot path (the weak-rung
    and lin-fastpath A/B numbers are pinned on it; the queue family
    clears its acceptance bar by <1%, so method-dispatch overhead is
    not free). A change to commit rules, ordering, or budgets in
    either implementation must be mirrored in the other; the
    cross-engine differential (tests/test_lin_fastpath.py
    TestStreamingCertifier, random cuts vs the one-shot) is the
    drift tripwire. The one-shot's `max_steps` abort budget is
    deliberately absent here: a feed's work is already bounded by the
    segment plus the length-scaled flip budget, and stream units have
    their own size caps (JGRAFT_STREAM_GREEDY_MAX_EVENTS)."""

    def __init__(self, model, budget: Optional[int] = None):
        from ..models.base import EncodedOp

        self._EncodedOp = EncodedOp
        self._model = model
        self._step = model.step
        self._readonly = frozenset(
            getattr(model, "readonly_fcodes", ()) or ())
        self._base_budget = budget
        # op table / event tape (append-only across feeds)
        self._ops: List[tuple] = []
        self._op_forced: List[bool] = []
        self._ev_ops: List[tuple] = []
        self._opened_by: List[int] = [0]
        self._active: dict = {}      # slot -> op id (spans feeds)
        # value-guide masks (grown per op; falls back to lookahead)
        self._guide_ok = (hasattr(model, "enable_values")
                          and hasattr(model, "observe_values"))
        self._dom: dict = {}
        self._em: List[int] = []
        self._om: List[int] = []
        # the carry proper
        self._state = model.init_state()
        self._done = 0
        self._pending: List[int] = []
        self._stack: deque = deque(maxlen=_BACKTRACK_STACK_CAP)
        self._pos = 0
        self._flips = 0
        self._dead = False

    # ------------------------------------------------------ accessors

    @property
    def certified(self) -> bool:
        """True while every settled event so far is covered by a
        complete legal witness (sound VALID for the settled prefix)."""
        return not self._dead

    @property
    def tier(self) -> Optional[str]:
        """Decided-tier attribution: "greedy" while the first-choice
        path carried, "backtrack" once any flip was spent; None once
        undecided."""
        if self._dead:
            return None
        return "greedy" if self._flips == 0 else "backtrack"

    def carry_state(self) -> dict:
        """The certifier's carry, for the resume-identity tests (a
        resumed session's replay must land field-for-field here)."""
        return {
            "pos": self._pos,
            "ops": len(self._ops),
            "done": self._done,
            "state": self._state,
            "pending": tuple(self._pending),
            "flips": self._flips,
            "stack_depth": len(self._stack),
            "dead": self._dead,
        }

    # ---------------------------------------------------------- guide

    def _guide_add(self, k: int) -> None:
        """(Re)compute op k's enable/observe masks — on OPEN, and again
        when its FORCE settles (the hooks may key on `forced`)."""
        if not self._guide_ok:
            return
        f, a, b = self._ops[k]
        eo = self._EncodedOp(f, a, b, self._op_forced[k])
        evs = self._model.enable_values(eo)
        ovs = self._model.observe_values(eo)
        if evs is None or ovs is None:
            self._guide_ok = False
            return
        masks = [0, 0]
        for j, vals in enumerate((evs, ovs)):
            for v in vals:
                if v not in self._dom:
                    if len(self._dom) >= 63:
                        self._guide_ok = False
                        return
                    self._dom[v] = len(self._dom)
                masks[j] |= 1 << self._dom[v]
        self._em[k], self._om[k] = masks

    # ----------------------------------------------------------- scan

    def feed(self, events) -> bool:
        """Consume one settled suffix ([n, 5] int32 rows) and advance
        the witness; returns `certified`."""
        rows = np.asarray(events).tolist() if len(events) else []
        for row in rows:
            et, slot = row[0], row[1]
            if et == EV_OPEN:
                k = len(self._ops)
                self._ops.append((row[2], row[3], row[4]))
                self._op_forced.append(False)
                self._em.append(0)
                self._om.append(0)
                self._active[slot] = k
                self._ev_ops.append((EV_OPEN, k))
                self._guide_add(k)
            elif et == EV_FORCE:
                k = self._active.pop(slot)
                self._op_forced[k] = True
                self._ev_ops.append((EV_FORCE, k))
                self._guide_add(k)
            else:
                self._ev_ops.append((0, -1))
            self._opened_by.append(
                self._opened_by[-1] + (1 if et == EV_OPEN else 0))
        if self._dead:
            return False
        return self._scan()

    def _sweep(self, state, done, pending) -> int:
        step, readonly, ops = self._step, self._readonly, self._ops
        for k in pending:
            if not (done >> k) & 1 and ops[k][0] in readonly \
                    and step(state, *ops[k])[1]:
                done |= 1 << k
        return done

    def _candidates(self, state, done, pending, e) -> list:
        step, ops = self._step, self._ops
        te = ops[e]
        legal_e = step(state, *te)[1]
        out = []
        if legal_e:
            out.append((-1, 0, 0, -1, None))
        for k in pending:
            if (done >> k) & 1 or k == e:
                continue
            s2, legal = step(state, *ops[k])
            if not legal:
                continue
            if self._guide_ok and not (self._em[k] & self._om[e]):
                enables = 1  # mask proves k exposes nothing e observes
            else:
                enables = 0 if step(s2, *te)[1] else 1
            out.append((0, enables,
                        0 if self._op_forced[k] else 1, k, k))
        out.sort(key=lambda t: t[:4])
        return [t[4] for t in out]

    def _scan(self) -> bool:
        """The certify_encoded main loop over the not-yet-consumed
        tape suffix, reading/writing the instance carry."""
        step, ops, ev_ops = self._step, self._ops, self._ev_ops
        readonly, opened_by = self._readonly, self._opened_by
        base = (self._base_budget if self._base_budget is not None
                else greedy_backtrack_budget())
        budget = _effective_budget(base, len(ev_ops))
        state, done, pending = self._state, self._done, self._pending
        stack, pos, flips = self._stack, self._pos, self._flips
        n_ev = len(ev_ops)
        ok = True
        while pos < n_ev:
            et, k = ev_ops[pos]
            if et == EV_OPEN:
                f, a, b = ops[k]
                if f in readonly and step(state, f, a, b)[1]:
                    done |= 1 << k
                else:
                    pending.append(k)
                pos += 1
                continue
            if et != EV_FORCE or (done >> k) & 1:
                pos += 1
                continue
            legal_k = step(state, *ops[k])[1]
            choice = None
            if legal_k:
                if budget > 0 and any(not (done >> o) & 1
                                      for o in pending):
                    stack.append([pos, state, done, None, 1])
            else:
                cands = self._candidates(state, done, pending, k)
                if cands:
                    if len(cands) > 1 and budget > 0:
                        stack.append([pos, state, done, cands, 1])
                    choice = cands[0]
                else:
                    while stack:
                        cp = stack[-1]
                        if cp[3] is None:
                            kc = ev_ops[cp[0]][1]
                            pc = [o for o in range(opened_by[cp[0]])
                                  if not (cp[2] >> o) & 1]
                            cp[3] = self._candidates(cp[1], cp[2], pc,
                                                     kc)
                        if cp[4] < len(cp[3]):
                            flips += 1
                            if flips > budget:
                                ok = False
                                break
                            pos, state, done = cp[0], cp[1], cp[2]
                            choice = cp[3][cp[4]]
                            cp[4] += 1
                            k = ev_ops[pos][1]
                            pending = [o for o in range(opened_by[pos])
                                       if not (done >> o) & 1]
                            break
                        stack.pop()
                    else:
                        ok = False  # no restorable choice — undecided
                    if not ok:
                        break
            commit = k if choice is None else choice
            state = step(state, *ops[commit])[0]
            done = self._sweep(state, done | (1 << commit), pending)
            if choice is None:
                pos += 1
            pending = [o for o in pending if not (done >> o) & 1]
        self._state, self._done, self._pending = state, done, pending
        self._pos, self._flips = pos, flips
        if not ok:
            self._dead = True
        return not self._dead


# ------------------------------------------------------------ batch entry


def apply_rung(encs: Sequence[EncodedHistory], model, consistency: str):
    """Certify/relax a batch at `consistency`. Returns (out, certified,
    tiers): `certified[i]` True where a witness already proves the row
    VALID at the rung (then `out[i]` is whichever encoding certified it
    and `tiers[i]` is "greedy" or "backtrack" — the decided-tier
    attribution); otherwise `out[i]` is the rung-relaxed encoding for
    the ordinary kernel ladder and `tiers[i]` is None.

    Certification order exploits monotone relaxation: a witness for the
    ORIGINAL (linearizable) stream is a witness for every weaker rung,
    and the original stream's FORCE order — real completion order, an
    approximation of the linearization order — is exactly the guidance
    the certifier needs, so it succeeds there on most valid histories
    and the row never pays the relaxation pass at all. Rows it misses
    relax and retry (the relaxed stream admits rung-only witnesses,
    e.g. stale reads); rows still undecided go to the kernels on the
    relaxed stream."""
    from .certify_batch import certify_many

    consistency = normalize_consistency(consistency)
    n = len(encs)
    out: list = list(encs)
    certified = [False] * n
    tiers: list = [None] * n
    greedy = greedy_on()
    # Pass 1: certify the ORIGINAL streams, batched across the rows
    # (checker/certify_batch.py — outcome-identical to the per-row
    # scalar loop; JGRAFT_CERTIFY_BATCH=0 restores it exactly).
    first = ([i for i in range(n) if encs[i].n_events > 0]
             if greedy else [])
    res = certify_many([encs[i] for i in first], model)
    for i, (ok, tier, _) in zip(first, res):
        if ok:
            certified[i] = True
            tiers[i] = tier
    # Pass 2: relax the misses and retry on the rung's stream.
    retry = [i for i in range(n) if not certified[i]]
    for i in retry:
        out[i] = relax_encoded(encs[i], model, consistency)
    if greedy:
        retry = [i for i in retry if out[i].n_events > 0]
        res = certify_many([out[i] for i in retry], model)
        for i, (ok, tier, _) in zip(retry, res):
            if ok:
                certified[i] = True
                tiers[i] = tier
    return out, certified, tiers

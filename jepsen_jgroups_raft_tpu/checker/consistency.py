"""Weaker-consistency rungs: relaxed-precedence scans over the packed
event tensors.

The ladder below full linearizability (ROADMAP item 4) is built from ONE
observation: the packed event stream (history/packing.py) encodes ALL
real-time precedence through FORCE placement — an op must linearize
between its OPEN and its FORCE. A weaker consistency rung is therefore a
*stream transform*, not a new engine: defer each op's FORCE along the
axis the rung cares about and re-run the identical frontier machinery
(dense/mask/sort kernels, macro compaction, chunked eviction, autotune
bucketing, graftd coalescing — all of it consumes `EncodedHistory` and
applies unchanged).

Rungs (strong → weak), by FORCE placement:

  ``linearizable``  — FORCE at the op's real-time completion (the
                      untouched encoding).
  ``sequential``    — FORCE deferred to just before the same process's
                      NEXT op opens (or end of stream): cross-process
                      real-time edges are dropped, per-process program
                      order is kept.
  ``session``       — (monotonic-reads tier) FORCE deferred to just
                      before the same process's next *read* opens
                      (``Model.readonly_fcodes``), else end of stream:
                      only reads must observe their session's earlier
                      ops.

Soundness (doc/checker-design.md §12 for the full argument):

  * Monotone relaxation: every rung only moves FORCEs later (clamped to
    ``max(original, deferred)``), so any linearization witness survives
    each step down the ladder — a history passing linearizability
    passes every weaker rung, and a FAIL at a weak rung certifies
    non-linearizability (the rung-ordering property tests pin both).
  * Positive certification: a ``sequential`` witness linearizes each op
    before its process's next op opens, hence before that op — the
    witness respects program order, so a PASS certifies sequential
    consistency. (The rung may be stricter than full SC: stream order
    still carries the cross-process edges the interval encoding cannot
    drop — exact SC checking is NP-hard and out of scope; this is the
    tractable interval-order relaxation.) A ``session`` PASS certifies
    that each read observes all earlier same-session ops — monotonic
    reads + read-your-writes.

Why the rungs are CHEAPER: a weaker rung admits more witnesses, so the
one-pass greedy certifier below (O(events · window), pure host scan, no
kernel launch) succeeds on the overwhelming majority of valid histories
— the measured A/B win (scripts/ab_consistency.py). Rows greedy cannot
certify fall through to the ordinary kernel ladder on the relaxed
stream; greedy never *refutes*, so its answers are sound by
construction (the committed order IS a witness).
"""

from __future__ import annotations

import heapq
from typing import List, Optional, Sequence

import numpy as np

from ..history.packing import EV_FORCE, EV_OPEN, EncodedHistory
from ..platform import env_int

#: Rung names, strongest first. Index = position in the ladder.
CONSISTENCY_LEVELS = ("linearizable", "sequential", "session")

_ALIASES = {
    "lin": "linearizable",
    "linearizability": "linearizable",
    "seq": "sequential",
    "monotonic-reads": "session",
    "monotonic": "session",
}


def normalize_consistency(name: Optional[str]) -> str:
    """Canonical rung name (aliases accepted); ValueError on unknowns —
    the service maps that to a 400 at admission, never into the queue."""
    if name is None:
        return "linearizable"
    n = _ALIASES.get(str(name).strip().lower(), str(name).strip().lower())
    if n not in CONSISTENCY_LEVELS:
        raise ValueError(
            f"unknown consistency {name!r}; valid: "
            f"{CONSISTENCY_LEVELS} (aliases: {sorted(_ALIASES)})")
    return n


def rung_index(name: str) -> int:
    return CONSISTENCY_LEVELS.index(normalize_consistency(name))


def greedy_on() -> bool:
    """Whether the greedy witness certifier runs before the kernel pass
    on weaker rungs. ``JGRAFT_GREEDY_CERTIFY=0`` disables it — the
    ablation arm (rung verdicts must be identical either way, pinned by
    tests) and the A/B denominator."""
    return env_int("JGRAFT_GREEDY_CERTIFY", 1, minimum=0) != 0


# ----------------------------------------------------- stream relaxation


def relax_encoded(enc: EncodedHistory, model,
                  consistency: str) -> EncodedHistory:
    """Re-encode one packed history with the rung's relaxed FORCE
    placement (module docstring). Pure host transform on the packed
    tensors; slot assignment is re-run so the relaxed stream is a
    first-class `EncodedHistory` every kernel family accepts.

    An encoding without per-event process ids (`proc is None` — hand
    built, or loaded from an older artifact) cannot be relaxed
    per-process; it is returned UNCHANGED, which is conservative and
    sound in both directions (the rung is then exactly linearizability
    for that row: a pass still implies the weaker guarantee, a fail
    still certifies non-linearizability)."""
    consistency = normalize_consistency(consistency)
    if consistency == "linearizable" or enc.n_events == 0:
        return enc
    proc = enc.proc
    if proc is None or len(proc) != enc.n_events:
        return enc
    events = enc.events
    op_index = enc.op_index
    readonly = frozenset(getattr(model, "readonly_fcodes", ()) or ())

    # -- decode the stream back into ops -------------------------------
    # op record: [open_pos, f, a, b, open_idx, pid, force_pos|-1,
    #             force_idx] (force_idx = the completion row's history
    #             index — FORCE rows must keep reporting it so rung
    #             counterexamples point at the completion, like the
    #             original encoding's op_index convention).
    ops: List[list] = []
    active: dict = {}          # slot -> op record index
    per_proc: dict = {}        # pid -> [op record index...] in open order
    for pos in range(enc.n_events):
        et = int(events[pos, 0])
        slot = int(events[pos, 1])
        if et == EV_OPEN:
            k = len(ops)
            ops.append([pos, int(events[pos, 2]), int(events[pos, 3]),
                        int(events[pos, 4]), int(op_index[pos]),
                        int(proc[pos]), -1, -1])
            active[slot] = k
            per_proc.setdefault(int(proc[pos]), []).append(k)
        elif et == EV_FORCE:
            k = active.pop(slot)
            ops[k][6] = pos
            ops[k][7] = int(op_index[pos])

    # -- per-process deferral targets ----------------------------------
    END = enc.n_events
    anchor: List[Optional[int]] = [None] * len(ops)  # forced ops only
    for pid, ks in per_proc.items():
        for j, k in enumerate(ks):
            if ops[k][6] < 0:
                continue  # optional op: never forced, nothing to move
            later = ks[j + 1:]
            if consistency == "sequential":
                cand = ops[later[0]][0] if later else END
            else:  # session: next same-process READ open
                cand = END
                for k2 in later:
                    if ops[k2][1] in readonly:
                        cand = ops[k2][0]
                        break
            # Monotone-relaxation clamp: never move a FORCE earlier
            # than its real-time position (ill-formed inputs included).
            anchor[k] = cand if cand > ops[k][6] else ops[k][6]

    # -- rebuild: opens at their positions, forces just before their
    # anchor opens (END = past everything); ties among deferred forces
    # keep original completion order. kind 0 (force) sorts before kind 1
    # (open) at the same anchor, which is exactly "just before".
    items = []
    for k, o in enumerate(ops):
        items.append((o[0], 1, k, EV_OPEN))
        if o[6] >= 0:
            items.append((anchor[k], 0, o[6], EV_FORCE, k))
    items.sort(key=lambda it: (it[0], it[1], it[2]))

    n_ev = len(items)
    out = np.zeros((n_ev, 5), dtype=np.int32)
    out_idx = np.empty(n_ev, dtype=np.int32)
    out_proc = np.empty(n_ev, dtype=np.int32)
    slot_of: dict = {}
    free: List[int] = []
    next_slot = 0
    for j, it in enumerate(items):
        if it[3] == EV_OPEN:
            k = it[2]
            if free:
                s = heapq.heappop(free)
            else:
                s = next_slot
                next_slot += 1
            slot_of[k] = s
            out[j] = (EV_OPEN, s, ops[k][1], ops[k][2], ops[k][3])
            out_idx[j] = ops[k][4]
        else:
            k = it[4]
            s = slot_of[k]
            out[j] = (EV_FORCE, s, 0, 0, 0)
            heapq.heappush(free, s)
            out_idx[j] = ops[k][7]
        out_proc[j] = ops[k][5]
    return EncodedHistory(events=out, op_index=out_idx,
                          n_slots=next_slot, n_ops=len(ops),
                          proc=out_proc)


# ------------------------------------------------------ greedy certifier


def greedy_certify(enc: EncodedHistory, model) -> bool:
    """One-pass witness construction on an encoded stream. Two commit
    rules build the order:

      * EAGER observations: a pending READ-ONLY op (an opcode the model
        declares in `readonly_fcodes` — never mutates at ANY state)
        that is legal NOW commits immediately — provably lossless: if
        any witness places a read-only op elsewhere, moving it to any
        legal point yields another witness, so committing at the first
        legal moment never forecloses anything. (The rule must key on
        the opcode, not on "step preserved the state here": a write
        that is a no-op at the CURRENT state can still be the mutation
        a later read depends on.) This is what lets reads that
        linearized early but completed late (the common shape under
        concurrency) certify without search.
      * LAZY mutations: a state-changing op commits only when its FORCE
        demands it, or when a forced op needs its effect (older pending
        ops are tried in open order).

      * FORCED-FIRST retries: when a forced op needs older effects, the
        retry pass offers ops that will themselves be forced (known
        outcomes) before optional crashed ops — an always-legal
        optional mutation (a crashed enqueue, an info add) committed
        too eagerly poisons every later exact observation, so the
        optionals are spent only when nothing forced helps.

    Returns True iff a complete legal witness was built — the committed
    order respects every op's [OPEN, FORCE] interval, so True is a
    sound VALID for whatever rung produced the stream. False means
    *undecided* (greedy took a wrong turn), never invalid; callers fall
    through to the exact kernel ladder."""
    state = model.init_state()
    step = model.step
    readonly = frozenset(getattr(model, "readonly_fcodes", ()) or ())
    events = enc.events.tolist()
    # Per-open forced-ness: does this open's slot see a FORCE before the
    # slot is reused? (Packing recycles a slot only at its FORCE, so the
    # next event on the slot answers directly.)
    next_on_slot: dict = {}
    forced_open = [False] * len(events)
    for pos in range(len(events) - 1, -1, -1):
        et, slot = events[pos][0], events[pos][1]
        if et == EV_OPEN:
            forced_open[pos] = next_on_slot.get(slot) == EV_FORCE
        next_on_slot[slot] = et

    # op record: [f, a, b, done, will_be_forced]
    pending: List[list] = []
    by_slot: dict = {}

    def sweep():
        # One pass suffices: read-only commits leave the state (the
        # only legality input) unchanged.
        for o in pending:
            if not o[3] and o[0] in readonly and \
                    step(state, o[0], o[1], o[2])[1]:
                o[3] = True

    for pos, row in enumerate(events):
        et, slot = row[0], row[1]
        if et == EV_OPEN:
            f, a, b = row[2], row[3], row[4]
            e = [f, a, b, False, forced_open[pos]]
            by_slot[slot] = e
            # Eager-commit at open when read-only and already legal
            # (the rest of `pending` was swept at this same state).
            if f in readonly and step(state, f, a, b)[1]:
                e[3] = True
            else:
                pending.append(e)
        elif et == EV_FORCE:
            e = by_slot.pop(slot)
            if e[3]:
                continue
            s2, legal = step(state, e[0], e[1], e[2])
            if legal:
                state = s2
                e[3] = True
                sweep()
            else:
                # Commit older pending ops (open order, forced tier
                # first) whose step is legal, re-trying the forced op
                # after each commit.
                while not e[3]:
                    progressed = False
                    for tier in (True, False):
                        for o in pending:
                            if o is e or o[3] or o[4] is not tier:
                                continue
                            s2, legal = step(state, o[0], o[1], o[2])
                            if not legal:
                                continue
                            state = s2
                            o[3] = True
                            progressed = True
                            sweep()
                            s3, l3 = step(state, e[0], e[1], e[2])
                            if l3:
                                state = s3
                                e[3] = True
                                sweep()
                            break
                        if progressed:
                            break
                    if not progressed:
                        return False  # undecided — kernel decides
            pending = [o for o in pending if not o[3]]
    return True


# ------------------------------------------------------------ batch entry


def apply_rung(encs: Sequence[EncodedHistory], model, consistency: str):
    """Certify/relax a batch at `consistency`. Returns (out, certified):
    `certified[i]` True where a greedy witness already proves the row
    VALID at the rung (then `out[i]` is whichever encoding certified
    it); otherwise `out[i]` is the rung-relaxed encoding for the
    ordinary kernel ladder.

    Certification order exploits monotone relaxation: a witness for the
    ORIGINAL (linearizable) stream is a witness for every weaker rung,
    and the original stream's FORCE order — real completion order, an
    approximation of the linearization order — is exactly the guidance
    the greedy scan needs, so it succeeds there on most valid
    histories and the row never pays the relaxation pass at all. Rows
    it misses relax and retry (the relaxed stream admits rung-only
    witnesses, e.g. stale reads); rows still undecided go to the
    kernels on the relaxed stream."""
    consistency = normalize_consistency(consistency)
    n = len(encs)
    out: list = list(encs)
    certified = [False] * n
    greedy = greedy_on()
    for i, e in enumerate(encs):
        if greedy and e.n_events > 0 and greedy_certify(e, model):
            certified[i] = True
            continue
        out[i] = relax_encoded(e, model, consistency)
        if greedy and out[i].n_events > 0 and \
                greedy_certify(out[i], model):
            certified[i] = True
    return out, certified

"""Performance checker: latency and throughput over time.

Equivalent of jepsen checker/perf (reference raft.clj:74): computes
latency quantiles and completion-rate series from the history, annotated
with nemesis activity windows (the reference shades nemesis intervals into
its gnuplot output, membership.clj:158-161). Renders SVG plots into the
store directory when one is available — no gnuplot dependency, just
generated SVG.
"""

from __future__ import annotations

import math
from pathlib import Path
from typing import List, Optional, Tuple

from ..history.ops import INFO, OK, History
from .base import Checker


def _quantile(sorted_xs: List[float], q: float) -> float:
    if not sorted_xs:
        return 0.0
    i = min(len(sorted_xs) - 1, int(q * len(sorted_xs)))
    return sorted_xs[i]


class PerfChecker(Checker):
    def __init__(self, bucket_s: float = 1.0, render: bool = True,
                 nemeses: Optional[List[dict]] = None):
        """`nemeses`: perf annotations from nemesis packages —
        {"name", "start": set, "stop": set, "color"} — the reference's
        colored nemesis intervals (membership.clj:158-161). Defaults to
        the stock fault vocabulary (FAULT_HEALS)."""
        self.bucket_s = bucket_s
        self.render = render
        self.heals = dict(FAULT_HEALS)
        self.colors: dict = {}
        for spec in nemeses or []:
            for s in spec.get("start", ()):
                for e in spec.get("stop", ()):
                    self.heals[s] = e
                if spec.get("color"):
                    self.colors[s] = spec["color"]

    def check(self, test, history, opts=None) -> dict:
        if not isinstance(history, History):
            history = History(history)
        pairs = history.client_ops().pairs()
        lat_by_f: dict = {}
        points: List[Tuple[float, float, str, str]] = []  # t, latency, f, type
        rate: dict = {}
        for p in pairs:
            if p.completion is None:
                continue
            t0, t1 = p.invoke.time, p.completion.time
            if t0 < 0 or t1 < 0:
                continue
            lat = (t1 - t0) / 1e9
            lat_by_f.setdefault(p.f, []).append(lat)
            points.append((t0 / 1e9, lat, p.f, p.completion.type))
            b = int(t1 / 1e9 / self.bucket_s)
            rate.setdefault(p.completion.type, {})
            rate[p.completion.type][b] = rate[p.completion.type].get(b, 0) + 1

        nemesis_windows = _nemesis_windows(history, self.heals)
        out = {"valid?": True, "latency": {}, "rate": {}}
        for f, lats in lat_by_f.items():
            lats.sort()
            out["latency"][f] = {
                "count": len(lats),
                "median": _quantile(lats, 0.5),
                "p95": _quantile(lats, 0.95),
                "p99": _quantile(lats, 0.99),
                "max": lats[-1],
            }
        for t, buckets in rate.items():
            # Mean over the elapsed span, not over occupied buckets — a
            # bursty history must not overstate its rate.
            span = (max(buckets) - min(buckets) + 1) * self.bucket_s
            out["rate"][t] = {"mean-hz": sum(buckets.values()) / span}
        out["nemesis-windows"] = nemesis_windows
        # Chunked event-scan counters (checker/schedule.py): how much
        # verification work the wavefront evicted/overlapped so far this
        # process — surfaced here so per-run stores carry the eviction
        # evidence next to the latency data (ISSUE 3 reporting path).
        scan = scan_stats_summary()
        if scan is not None:
            out["scan-stats"] = scan
        # Autotune evidence (PR 6): which per-bucket plans this process
        # has loaded/measured so far — absent when the autotuner never
        # engaged (off, or every group below the work gates).
        tune = autotune_summary()
        if tune is not None:
            out["autotune"] = tune
        # Tier attribution (ISSUE 13): which decision-ladder tier
        # decided this run's verdicts, with per-tier wall time — at
        # fleet scale the cheap-tier decided fraction IS the capacity
        # model, so the per-run store carries it next to the scan
        # counters. Lin-rung fast-path hits are namespaced
        # ``greedy@lin``/``backtrack@lin`` (ISSUE 14) so the fleet view
        # never conflates the weak-rung certifier's hit-rate with the
        # linearizable fast path's.
        tiers = tier_summary()
        if tiers is not None:
            out["decided-tiers"] = tiers
        # Lin fast-path engagement (ISSUE 14): scanned/certified/gated
        # row counts + certify wall — the hit-rate evidence beside the
        # per-bucket gating store's persisted records.
        fp = lin_fastpath_summary()
        if fp is not None:
            out["lin-fastpath"] = fp
        # Exact-cycle tier counters (ISSUE 19): size skips (the
        # previously-invisible cap skip), condensation effectiveness
        # (nodes pre/post, SCC hits) and blocked-closure tile volume —
        # absent when the tier never touched a graph this run.
        cyc = cycle_stats_summary()
        if cyc is not None:
            out["cycle-stats"] = cyc
        store_dir = (test or {}).get("store_dir")
        if self.render and store_dir:
            try:
                path = Path(store_dir) / "latency.svg"
                path.write_text(
                    _latency_svg(points, nemesis_windows, colors=self.colors))
                out["plot"] = str(path)
            except Exception:  # plotting must never fail a run
                pass
        return out


def format_scan_stats(scan: dict):
    """Result-dict form of a raw schedule counter dict, or None when it
    holds no chunked work (absent beats all-zero in stored results).
    Shared by `scan_stats_summary` and the runner's post-check stamp."""
    if not scan.get("groups_run"):
        return None
    return {"chunks-run": scan["chunks_run"],
            "evicted-rows": scan["evicted_rows"],
            "groups-run": scan["groups_run"],
            "groups-early-exited": scan["groups_early_exited"],
            "pipeline-overlap-s": round(scan["pipeline_overlap_s"], 3)}


def scan_stats_summary():
    """Per-run chunked-scan wavefront counters (checker/schedule.py),
    or None when no chunked group has run — absent beats all-zero in
    stored results. Reads the innermost active `stats_scope` (the one
    `core/runner.run_test` opens around each test's checking phase), so
    back-to-back runs in one process store their OWN counters instead
    of a process-lifetime accumulation; outside any scope (direct
    checker use) it falls back to the process totals. NOTE the composed
    checker runs perf BEFORE the workload checker, so within run_test
    this block is usually absent from the perf sub-result — the
    authoritative per-run counters are stamped by the RUNNER after the
    whole composed check completes (`core/runner.run_test`)."""
    from .schedule import snapshot_stats

    return format_scan_stats(snapshot_stats(scoped=True))


def autotune_summary():
    """Process-level autotuner counters (checker/autotune.py), or None
    when the autotuner has not engaged — absent beats all-zero in
    stored results, same stance as the scan counters."""
    from .autotune import snapshot_counters

    c = snapshot_counters()
    if not any(c.values()):
        return None
    return {"plans-loaded": c["plans_loaded"],
            "plans-measured": c["plans_measured"],
            "plan-misses": c["plan_misses"]}


def lin_fastpath_summary():
    """Process-level lin-fastpath counters
    (checker/linearizable.fastpath_counters), or None when the fast
    path never engaged — absent beats all-zero in stored results, same
    stance as the autotune block."""
    from .linearizable import fastpath_counters

    c = fastpath_counters()
    if not any(c.values()):
        return None
    return {"rows-scanned": c["rows_scanned"],
            "rows-certified": c["rows_certified"],
            "rows-gated": c["rows_gated"],
            "rows-rung-skipped": c["rows_rung_skipped"],
            "certify-wall-s": round(c["certify_wall_s"], 4)}


def format_cycle_stats(scan: dict):
    """Result-dict form of the cycle-tier counters riding a raw
    schedule counter dict, or None when the tier never built a graph
    and never skipped one (absent beats all-zero in stored results).
    ``size-skipped-rows`` is the ISSUE-19 satellite: rows whose
    required-op graph exceeded JGRAFT_CYCLE_MAX_OPS used to vanish
    from every stats surface."""
    keys = ("cycle_size_skips", "cycle_nodes_pre", "cycle_nodes_post",
            "cycle_scc_hits", "cycle_tiles_run")
    if not any(scan.get(k) for k in keys):
        return None
    return {"size-skipped-rows": scan.get("cycle_size_skips", 0),
            "nodes-pre-condense": scan.get("cycle_nodes_pre", 0),
            "nodes-post-condense": scan.get("cycle_nodes_post", 0),
            "scc-hits": scan.get("cycle_scc_hits", 0),
            "tiles-run": scan.get("cycle_tiles_run", 0)}


def cycle_stats_summary():
    """Per-run cycle-tier counters (checker/schedule.note_cycle), or
    None when the tier never engaged. Scoped like
    `scan_stats_summary` — the innermost active `stats_scope` wins."""
    from .schedule import snapshot_stats

    return format_cycle_stats(snapshot_stats(scoped=True))


def format_tier_stats(tiers: dict):
    """Result-dict form of a raw per-tier counter dict ({tier: {"rows",
    "wall_s"}}), or None when nothing was decided. Reports decided row
    counts, the decided FRACTION per tier (the fleet capacity metric),
    and per-tier wall seconds."""
    total = sum(v["rows"] for v in tiers.values())
    if not total:
        return None
    return {
        "decided-rows": {k: v["rows"] for k, v in tiers.items()},
        "decided-fraction": {k: round(v["rows"] / total, 4)
                             for k, v in tiers.items()},
        "wall-s": {k: round(v["wall_s"], 4) for k, v in tiers.items()},
    }


def tier_summary():
    """Per-run tier-attribution counters (checker/schedule.note_tier),
    or None when nothing was decided. Scoped like
    `scan_stats_summary` — the innermost active `stats_scope` wins, so
    back-to-back runs store their own fractions."""
    from .schedule import snapshot_tiers

    return format_tier_stats(snapshot_tiers(scoped=True))


#: fault-op f → healing-op f (the start/stop convention nemesis packages
#: follow; the reference's packages shade exactly these spans into perf
#: plots, membership.clj:158-161).
FAULT_HEALS = {
    "start-partition": "stop-partition",
    "pause": "resume",
    "kill": "restart",
    "shrink": "grow",
}


def _nemesis_windows(history: History,
                     heals: Optional[dict] = None) -> List[dict]:
    """Fault activity windows: from the *completion* of a fault op to the
    completion of its healing op. The runner records each nemesis action
    twice (invocation then completion, both type info), so per f the 2nd,
    4th, ... occurrences are completions."""
    heals = FAULT_HEALS if heals is None else heals
    starters = set(heals)
    stoppers = {v: k for k, v in heals.items()}
    seen: dict = {}
    open_at: dict = {}  # fault f -> start time
    windows: List[dict] = []
    for op in history.nemesis_ops():
        f = op.f
        seen[f] = seen.get(f, 0) + 1
        if seen[f] % 2 == 1:
            continue  # invocation record; windows anchor on completions
        if f in starters and f not in open_at:
            # A refused/failed fault (guardrail refusal string, {"error"}
            # value, errored op) injected nothing: no window.
            failed = (op.error is not None
                      or isinstance(op.value, str)
                      or (isinstance(op.value, dict) and "error" in op.value))
            if failed:
                continue
            open_at[f] = op.time
        elif f in stoppers:
            started = open_at.pop(stoppers[f], None)
            if started is not None:
                windows.append({"f": stoppers[f], "start": started / 1e9,
                                "end": op.time / 1e9})
    for f, t in open_at.items():
        windows.append({"f": f, "start": t / 1e9, "end": None})
    return windows


_TYPE_COLOR = {OK: "#2a7", INFO: "#fa0", "fail": "#d33"}


def _latency_svg(points, windows, w: int = 900, h: int = 360,
                 colors: Optional[dict] = None) -> str:
    """Scatter of op latency over time, log-y, nemesis windows shaded."""
    colors = colors or {}
    if not points:
        return "<svg xmlns='http://www.w3.org/2000/svg'/>"
    tmax = max(p[0] for p in points) or 1.0
    lmin = max(1e-5, min(p[1] for p in points if p[1] > 0) if any(
        p[1] > 0 for p in points) else 1e-4)
    lmax = max(p[1] for p in points) or 1.0
    pad = 45

    def x(t):
        return pad + (w - 2 * pad) * t / tmax

    def y(lat):
        lat = max(lat, lmin)
        return h - pad - (h - 2 * pad) * (
            (math.log10(lat) - math.log10(lmin))
            / max(1e-9, math.log10(lmax) - math.log10(lmin)))

    parts = [
        f"<svg xmlns='http://www.w3.org/2000/svg' width='{w}' height='{h}' "
        f"font-family='sans-serif' font-size='11'>",
        f"<rect width='{w}' height='{h}' fill='white'/>",
    ]
    for win in windows:
        end = win["end"] if win["end"] is not None else tmax
        fill = colors.get(win["f"], "#f6c")
        parts.append(
            f"<rect x='{x(win['start']):.1f}' y='{pad}' "
            f"width='{max(1.0, x(end) - x(win['start'])):.1f}' "
            f"height='{h - 2 * pad}' fill='{fill}' opacity='0.15'/>")
    for t, lat, f, typ in points:
        parts.append(
            f"<circle cx='{x(t):.1f}' cy='{y(lat):.1f}' r='1.6' "
            f"fill='{_TYPE_COLOR.get(typ, '#888')}' opacity='0.7'/>")
    parts.append(
        f"<line x1='{pad}' y1='{h - pad}' x2='{w - pad}' y2='{h - pad}' "
        f"stroke='#333'/>"
        f"<line x1='{pad}' y1='{pad}' x2='{pad}' y2='{h - pad}' stroke='#333'/>"
        f"<text x='{w // 2}' y='{h - 8}'>time (s)</text>"
        f"<text x='4' y='{h // 2}' transform='rotate(-90 10 {h // 2})'>"
        f"latency (s, log)</text></svg>")
    return "".join(parts)

"""Transactional anomaly rung: G0 / G1c / G-single certification (ISSUE 19).

PAPER.md's L0 layer is history verification, and ecosystem-wide the
transactional half of that story is Elle: build the dependency graph a
serializable execution must respect, label every edge with its class,
and read the anomaly CLASS off the cheapest cycle that exists.  This
module lands that rung on the cycle-tier substrate (checker/cycle.py):
edge-class-labeled adjacency planes, SCC condensation, and transitive
closure over class-restricted submatrices — the blocked closure kernel
(ops/kernel_ir.make_cycle_closure_tiled) where a launch pays for
itself, host numpy/Tarjan otherwise.

Two plane sources share the certifier:

  * **Register-shaped histories** — `checker.cycle.build_sc_graph(...,
    want_planes=True)` labels the PR-13 edges it already derives
    (po = session order, wr = reads-from, ww = reads-from into an op
    that itself writes, rw = anti-dependency + reads-of-initial).
  * **List-append histories** (`build_txn_graph` here) — the Elle
    inference, multi-key: ops are ``("append", (k, e))`` /
    ``("read", (k, list))`` against per-key append-only lists, and a
    required observation of list L on key k yields
      - **wr**:  append(last L) → observer (the observer read exactly
        the state L, whose final element only that append installs);
      - **ww**:  append(L[i]) → append(L[i+1]) for consecutive pairs
        (state is append-only, so the observed order IS the write
        order; a completed append contributes its written list
        prev + [e], ordering itself after its observed predecessor);
      - **rw**:  observer → every required append of an element ∉ L on
        k (append-only lists never drop elements, so an append missing
        from the observed state must linearize after the observation);
      - **po**:  session order, across keys — the only edge class that
        crosses keys, and exactly what lets a cross-key cycle exist
        while every single-key projection stays serializable (the
        sharper-than-relaxation acceptance shape).

    Required ops are the forced (ok) ones; a crashed append joins only
    when its element is observed by a required op (it must have taken
    effect — the same unique-writer pull as the register graph).
    Elements appended more than once per key are unidentifiable:
    conservatively they contribute no wr/ww edges (rw edges stay sound
    — EVERY append of a missing element must follow the observer).

Anomaly classes over the planes (Adya / Elle, session flavor — po
rides along because our transactions are single ops and the session is
the transaction boundary evidence):

  * **G0**  — cycle in po ∪ ww (write-order contradiction);
  * **G1c** — cycle in po ∪ ww ∪ wr needing a wr edge (reported only
    when G0 is clean: the sharpest class wins);
  * **G-single** — exactly one rw edge closes an otherwise po∪ww∪wr
    path: rw edge (u, v) with v ⇝ u in the closure of po ∪ ww ∪ wr.

Soundness is the cycle-tier argument verbatim (doc/checker-design.md
§21): every edge holds in every legal serial execution of the required
ops, so a cycle in any plane subset proves no such execution exists —
the class only names WHICH guarantee broke.  Certification runs per
non-trivial SCC of the full union graph (condensation pre-pass,
JGRAFT_CYCLE_CONDENSE) since every cycle of every subset lives inside
one; G-single's reachability closure is the kernel's job on big
components, host squaring elsewhere — all routing, never verdicts.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ..history.ops import FAIL, OK, History
from .base import Checker
from .cycle import (_condense_env, _use_kernel, closure_fn, cycle_max_ops,
                    cycle_witness, host_has_cycle, tarjan_scc)

PLANE_NAMES = ("po", "ww", "wr", "rw")


# ------------------------------------------------- list-append inference


def _keyed(value) -> Optional[tuple]:
    """(key, payload) from a tuple/list-shaped op value, else None."""
    if isinstance(value, (tuple, list)) and len(value) == 2:
        return value[0], value[1]
    return None


def _obs_list(payload) -> Optional[List[int]]:
    """A well-formed observed list (ints), else None (malformed
    observations contribute no edges — conservative, never unsound)."""
    if isinstance(payload, (tuple, list)) and \
            all(isinstance(e, int) for e in payload):
        return list(payload)
    return None


def build_txn_graph(history: History) -> Optional[dict]:
    """Multi-key list-append dependency graph with edge-class planes,
    the skip marker {"skipped-nodes": n} past cycle_max_ops(), or None
    when the history holds no append/read ops (nothing to certify).
    Returns {"n", "adj", "planes", "op_index"} — adj is exactly the
    union of the planes."""
    if not isinstance(history, History):
        history = History(history)
    # (kind, key, elem | obs, pid, hist_index, forced, written_list)
    ops: List[tuple] = []
    for p in history.client_ops().pairs():
        kv = _keyed(p.invoke.value)
        if p.f == "append":
            if p.ctype == FAIL or kv is None or \
                    not isinstance(kv[1], int):
                continue
            written = None
            if p.ctype == OK:
                ckv = _keyed(p.completion.value)
                obs = _obs_list(ckv[1]) if ckv else None
                # the recorded result must actually end with the
                # appended element; otherwise keep the op as an
                # observation-free append (element evidence only)
                if obs and obs[-1] == kv[1]:
                    written = obs
            ops.append(("append", kv[0], kv[1], p.invoke.process,
                        p.invoke.index, p.ctype == OK, written))
        elif p.f == "read":
            if p.ctype != OK:
                continue
            ckv = _keyed(p.completion.value)
            obs = _obs_list(ckv[1]) if ckv else None
            if ckv is None or obs is None:
                continue
            ops.append(("read", ckv[0], None, p.invoke.process,
                        p.invoke.index, True, obs))
    if not ops:
        return None

    # appends per (key, element) — identification needs uniqueness
    appends: Dict[tuple, List[int]] = {}
    for k, op in enumerate(ops):
        if op[0] == "append":
            appends.setdefault((op[1], op[2]), []).append(k)

    def observation(k: int) -> Optional[List[int]]:
        return ops[k][6]

    # required = forced ∪ (appends whose element a required op
    # observed); a pulled-in crashed append carries no observation, so
    # one pass reaches the fixpoint
    required = {k for k, op in enumerate(ops) if op[5]}
    for k in sorted(required):
        obs = observation(k)
        if obs is None:
            continue
        key = ops[k][1]
        for e in obs:
            required.update(appends.get((key, e), []))
    if len(required) > cycle_max_ops():
        return {"skipped-nodes": len(required)}

    order = sorted(required, key=lambda k: ops[k][4])
    node = {k: i for i, k in enumerate(order)}
    n = len(order)
    adj = np.zeros((n, n), dtype=np.uint8)
    planes = {c: np.zeros((n, n), dtype=np.uint8) for c in PLANE_NAMES}

    def edge(cls_name, u, v):
        if u != v:
            adj[u, v] = 1
            planes[cls_name][u, v] = 1

    # po: consecutive required ops per process, across keys
    last_of: dict = {}
    for k in order:
        pid = ops[k][3]
        if pid in last_of:
            edge("po", node[last_of[pid]], node[k])
        last_of[pid] = k

    def unique_append(key, e) -> Optional[int]:
        ws = appends.get((key, e), [])
        return ws[0] if len(ws) == 1 and ws[0] in required else None

    req_appends: Dict[object, List[int]] = {}
    for k in order:
        if ops[k][0] == "append":
            req_appends.setdefault(ops[k][1], []).append(k)

    for k in order:
        obs = observation(k)
        if obs is None:
            continue
        key = ops[k][1]
        # wr: the observer read exactly the state ending in obs[-1]
        if obs:
            w = unique_append(key, obs[-1])
            if w is not None and w != k:
                edge("wr", node[w], node[k])
        # ww: observed element order IS append order (append-only)
        for ei, ej in zip(obs, obs[1:]):
            u, v = unique_append(key, ei), unique_append(key, ej)
            if u is not None and v is not None:
                edge("ww", node[u], node[v])
        # rw: appends of elements missing from the observed state must
        # come after it (every copy of them — sound under duplicates)
        seen = set(obs)
        for a in req_appends.get(key, []):
            if a != k and ops[a][2] not in seen:
                edge("rw", node[k], node[a])
    np.fill_diagonal(adj, 0)
    for p in planes.values():
        np.fill_diagonal(p, 0)
    return {"n": n, "adj": adj, "planes": planes,
            "op_index": [ops[k][4] for k in order]}


# --------------------------------------------------------- certification


def _closure_reach(adj: np.ndarray, kernel: Optional[bool]) -> np.ndarray:
    """Boolean transitive closure of one matrix: the batched closure
    kernel (monolithic or blocked, by bucket) when routed on, host
    float32 squaring otherwise (counts stay well under the f32 exact
    integer range; re-binarized every step)."""
    n = int(adj.shape[0])
    use_kernel = _use_kernel() if kernel is None else kernel
    if use_kernel and n >= 2:
        from ..history.packing import bucket_rows
        from .schedule import note_cycle

        N = bucket_rows(n, 4)
        kfn, tiles = closure_fn(N)
        if kfn is not None:
            batch = np.zeros((1, N, N), dtype=np.int32)
            batch[0, :n, :n] = adj
            _has, closed = kfn(batch)
            if tiles > 1:
                note_cycle(cycle_tiles_run=tiles)
            return np.asarray(closed)[0, :n, :n] > 0  # lint: allow(host-sync)
    a = adj.astype(np.float32)
    for _ in range(max(1, (max(n, 2) - 1).bit_length())):
        nxt = ((a > 0) | ((a @ a) > 0)).astype(np.float32)
        if np.array_equal(nxt, a):
            break
        a = nxt
    return a > 0


def _certify_component(planes: Dict[str, np.ndarray],
                       op_of: List[int],
                       kernel: Optional[bool]) -> dict:
    """Class certification over one (sub)graph's planes. Witnesses are
    minimized: shortest cycle through the earliest reachable node
    (cycle_witness's BFS), history op indices."""
    out: dict = {"G0": None, "G1c": None, "G-single": None}
    c0 = (planes["po"] | planes["ww"]).astype(np.uint8)
    c1 = (c0 | planes["wr"]).astype(np.uint8)

    def wit(sub: np.ndarray) -> Optional[List[int]]:
        path = cycle_witness(sub)
        return [op_of[v] for v in path] if path else None

    if host_has_cycle(c0):
        out["G0"] = {"cycle": wit(c0)}
        return out
    if host_has_cycle(c1):
        out["G1c"] = {"cycle": wit(c1)}
        return out
    # G-single: one rw edge closing a po∪ww∪wr path — the WEAKEST
    # class, only consulted when G0/G1c are clean (the sharpest class
    # names the anomaly; a G0 cycle would make any G-single report
    # redundant noise)
    rw_edges = np.argwhere(planes["rw"] > 0)
    if len(rw_edges):
        reach = _closure_reach(c1, kernel)
        best: Optional[List[int]] = None
        best_edge = None
        for u, v in rw_edges:
            u, v = int(u), int(v)
            if not reach[v, u]:
                continue
            path = _shortest_path(c1, v, u)
            if path is not None and (best is None
                                     or len(path) < len(best) - 1):
                best = [u] + path
                best_edge = (u, v)
        if best is not None:
            out["G-single"] = {"cycle": [op_of[v] for v in best],
                               "rw-edge": [op_of[best_edge[0]],
                                           op_of[best_edge[1]]]}
    return out


def _shortest_path(adj: np.ndarray, src: int, dst: int
                   ) -> Optional[List[int]]:
    """BFS path src → dst (inclusive), None when unreachable.
    src == dst returns [src] (the rw edge is itself the cycle)."""
    if src == dst:
        return [src]
    n = int(adj.shape[0])
    prev = np.full(n, -1, dtype=np.int64)
    prev[src] = src
    q = [src]
    qi = 0
    while qi < len(q):
        v = q[qi]
        qi += 1
        for w in np.flatnonzero(adj[v]):
            w = int(w)
            if prev[w] >= 0:
                continue
            prev[w] = v
            if w == dst:
                path = [dst]
                while path[-1] != src:
                    path.append(int(prev[path[-1]]))
                path.reverse()
                return path
            q.append(w)
    return None


def certify_planes(g: dict, kernel: Optional[bool] = None) -> dict:
    """Anomaly certification over one plane-labeled graph: SCC
    condensation first (every cycle of every plane subset lies inside
    a non-trivial SCC of the union — no SCC means all three classes
    are clean with no closure at all), per-component class checks
    after. JGRAFT_CYCLE_CONDENSE=0 forces the direct whole-graph arm
    (verdict-identical, pinned by tests)."""
    from .schedule import note_cycle

    n = g["n"]
    note_cycle(cycle_nodes_pre=n)
    condense = _condense_env()
    condense = True if condense is None else condense
    anomalies: dict = {"G0": None, "G1c": None, "G-single": None}
    if condense:
        comps = tarjan_scc(g["adj"])
        nontrivial = sorted((sorted(c) for c in comps if len(c) >= 2),
                            key=lambda c: c[0])
        note_cycle(cycle_nodes_post=len(comps),
                   cycle_scc_hits=len(nontrivial))
        for comp in nontrivial:
            idx = np.ix_(comp, comp)
            sub_planes = {c: p[idx] for c, p in g["planes"].items()}
            sub = _certify_component(
                sub_planes, [g["op_index"][v] for v in comp], kernel)
            for cls_name, hit in sub.items():
                if hit is not None and anomalies[cls_name] is None:
                    anomalies[cls_name] = hit
    else:
        anomalies = _certify_component(g["planes"], g["op_index"], kernel)
    # the sharpest class wins globally too (components are certified
    # independently, so a G0 in one and a G-single in another must
    # still collapse to the G0 name — identical to what the direct arm
    # reports, where _certify_component already stops at the sharpest)
    if anomalies["G0"] is not None:
        anomalies["G1c"] = None
        anomalies["G-single"] = None
    elif anomalies["G1c"] is not None:
        anomalies["G-single"] = None
    return anomalies


def certify_history(history, kernel: Optional[bool] = None) -> dict:
    """One history's transactional-anomaly verdict:
    {"valid?": True/False/"unknown", "anomalies": {class: witness},
    "nodes": n} — "unknown" + "skipped-size" when the graph exceeds
    the node cap (the stamped skip, never a silent pass)."""
    from .base import UNKNOWN
    from .schedule import note_cycle

    g = build_txn_graph(history)
    if g is None:
        return {"valid?": True, "anomalies": {}, "nodes": 0}
    if "adj" not in g:
        note_cycle(cycle_size_skips=1)
        return {"valid?": UNKNOWN, "anomalies": {},
                "skipped-size": g["skipped-nodes"],
                "cycle-skipped-size": g["skipped-nodes"]}
    anomalies = certify_planes(g, kernel)
    found = {k: v for k, v in anomalies.items() if v is not None}
    return {"valid?": not found, "anomalies": found, "nodes": g["n"]}


class TxnAnomalyChecker(Checker):
    """Composable checker façade over `certify_history`: the
    list-append workload composes it beside the per-key linearizable
    checker, so runs refute cross-key serializability violations the
    per-key rungs honestly cannot see."""

    def check(self, test, history, opts=None) -> dict:
        try:
            return certify_history(history)
        except Exception as e:  # evidence must never crash a run
            return {"valid?": "unknown",
                    "error": f"{type(e).__name__}: {e}"}


def certify_submission(histories: Sequence) -> dict:
    """graftd admission hook (service/request.admit): certify each
    submitted multi-key history and merge — any anomaly refutes the
    submission even when every per-key unit passes its rung. Kept
    host-only (kernel=False): admission runs on the HTTP thread and
    must not launch device work."""
    per = [certify_history(h, kernel=False) for h in histories]
    from .base import merge_valid

    return {"valid?": merge_valid(r["valid?"] for r in per),
            "histories": per}

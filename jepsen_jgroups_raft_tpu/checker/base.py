"""Checker protocol and composition.

Equivalent of jepsen.checker/Checker + checker/compose
(reference raft.clj:73-77). A checker examines a completed history and
returns a map with at least ``valid?``, which is True, False, or
``"unknown"`` (e.g. the search exceeded its budget). Composition ANDs
validity: any False → False; else any unknown → unknown; else True.
"""

from __future__ import annotations

from typing import Dict

VALID = True
INVALID = False
UNKNOWN = "unknown"


class Checker:
    def check(self, test: dict, history, opts: dict | None = None) -> dict:
        raise NotImplementedError


class ComposedChecker(Checker):
    def __init__(self, checkers: Dict[str, Checker]):
        self.checkers = dict(checkers)

    def check(self, test, history, opts=None) -> dict:
        results = {}
        for name, c in self.checkers.items():
            try:
                results[name] = c.check(test, history, opts or {})
            except Exception as e:  # a crashing checker must not eat a run
                results[name] = {
                    "valid?": UNKNOWN,
                    "error": f"checker raised: {e!r}",
                }
        return {"valid?": merge_valid(r.get("valid?") for r in results.values()),
                **results}


def compose(checkers: Dict[str, Checker]) -> ComposedChecker:
    return ComposedChecker(checkers)


def merge_valid(vs) -> object:
    out = VALID
    for v in vs:
        if v is INVALID:
            return INVALID
        if v is not VALID:
            out = UNKNOWN
    return out

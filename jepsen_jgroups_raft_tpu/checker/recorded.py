"""Re-verify recorded run histories as one on-device batch.

BASELINE config #3 ("CAS register + partition nemesis, 512 recorded
histories batched-verified") names the real unit of production work: not
synthetic histories, but histories a cluster actually produced, loaded
back from the store and verified together. This module is that path:

  store/<name>/<ts>/history.jsonl  →  load  →  per-key split (independent
  workloads, reference register.clj:106)  →  one vmapped kernel batch per
  model family across every sub-history of every run  →  per-run
  verdicts. (Invariant-style checkers without the Model step interface —
  the election-safety LeaderModel — run their direct host check instead.)

Exposed on the CLI as `python -m jepsen_jgroups_raft_tpu check RUN_DIR…` —
re-analysis of stored runs, a capability the reference reaches by re-running
jepsen's analysis against store/ directories.
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Optional, Sequence

from ..models import CasRegister, Counter, GSet, TicketQueue
from ..models.base import Model
from ..models.leader import MajorityLeaderModel
from .base import INVALID, UNKNOWN, VALID, merge_valid
from .independent import split_by_key
from .linearizable import check_histories

#: workload → (model factory, values are (key, value) tuples?)
#: Election re-checks use MajorityLeaderModel, not the parity
#: LeaderModel: a store written by a live run with --majority-election
#: carries `views` ops whose cross-node invariant would otherwise
#: silently weaken on re-verification (round-3 advisor finding); with no
#: views ops in the history it degrades exactly to the parity check.
WORKLOAD_MODELS = {
    "single-register": (CasRegister, True),
    "multi-register": (CasRegister, True),
    "counter": (Counter, False),
    "election": (MajorityLeaderModel, False),
    "set": (GSet, False),
    "queue": (TicketQueue, False),
}


def _run_workload(run_dir: Path) -> Optional[str]:
    try:
        with open(run_dir / "test.json") as f:
            t = json.load(f)
        # compose_test keeps the raw CLI opts under "opts"; the workload
        # name lives there (top-level checked too for hand-built tests).
        return t.get("workload") or t.get("opts", {}).get("workload")
    except (OSError, json.JSONDecodeError):
        return None


def load_run_histories(run_dir, workload: Optional[str] = None):
    """Load one run dir → (model, [per-key sub-histories], workload)."""
    from ..core.store import load_history

    run_dir = Path(run_dir)
    workload = workload or _run_workload(run_dir)
    if workload not in WORKLOAD_MODELS:
        raise ValueError(
            f"{run_dir}: unknown workload {workload!r}; pass --workload")
    model_factory, independent = WORKLOAD_MODELS[workload]
    history = load_history(run_dir).client_ops()
    if independent:
        subs = list(split_by_key(history).values())
    else:
        subs = [history]
    return model_factory(), subs, workload


def check_recorded(run_dirs: Sequence, workload: Optional[str] = None,
                   algorithm: str = "auto",
                   n_configs: Optional[int] = None) -> dict:
    """Batch-verify recorded runs. Sub-histories across all runs are
    grouped by model family; each frontier-model group goes through one
    check_histories batch, non-Model invariant checkers (LeaderModel) run
    their direct check per history. Returns a summary dict with per-run
    verdicts and throughput."""
    loaded = []  # (run_dir, model, subs)
    for d in run_dirs:
        model, subs, wl = load_run_histories(d, workload)
        loaded.append((str(d), model, subs, wl))

    t0 = time.perf_counter()
    # Group by model type so each batch is one kernel family.
    per_run: dict = {d: [] for d, _, _, _ in loaded}
    by_model: dict = {}
    for d, model, subs, _ in loaded:
        by_model.setdefault(type(model).__name__, (model, []))[1].extend(
            (d, s) for s in subs)
    n_histories = 0
    for _, (model, tagged) in by_model.items():
        hists = [s for _, s in tagged]
        if not hists:
            continue
        n_histories += len(hists)
        if not isinstance(model, Model):
            # Not a frontier-search model: invariant checkers like
            # LeaderModel (election safety is order-independent) expose a
            # direct `check(history)` instead of the Model step interface
            # (models/leader.py), matching the live checker's routing.
            results = [model.check(h) for h in hists]
        else:
            results = check_histories(hists, model, algorithm=algorithm,
                                      n_configs=n_configs)
            if isinstance(model, Counter):
                # Same tier ladder as the live counter workload
                # (workload/counter.py CounterChecker): a recorded
                # canonical-envelope counter run must not re-check to
                # UNKNOWN when the bounds tier can decide it.
                from .counter_bounds import decide_unknown_with_interval
                for j, r in enumerate(results):
                    if r.get("valid?") is UNKNOWN:
                        results[j] = decide_unknown_with_interval(
                            r, hists[j])
        for (d, _), r in zip(tagged, results):
            per_run[d].append(r)
    dt = time.perf_counter() - t0

    run_verdicts = {
        d: merge_valid(r.get("valid?") for r in rs) if rs else VALID
        for d, rs in per_run.items()
    }
    all_results = [r for rs in per_run.values() for r in rs]
    return {
        "valid?": merge_valid(run_verdicts.values()),
        "runs": len(loaded),
        "histories": n_histories,
        "n-valid": sum(1 for r in all_results if r.get("valid?") is VALID),
        "n-invalid": sum(1 for r in all_results
                         if r.get("valid?") is INVALID),
        "n-unknown": sum(1 for r in all_results
                         if r.get("valid?") is UNKNOWN),
        "time-s": dt,
        "histories-per-sec": (n_histories / dt) if dt > 0 else 0.0,
        "run-verdicts": run_verdicts,
    }

"""Checker framework.

Equivalent surface: jepsen.checker as used by the reference —
compose / perf / stats / unhandled-exceptions / linearizable /
timeline (reference raft.clj:73-77, register.clj:106-111,
counter.clj:133-137, leader.clj:81-85) — plus knossos itself, whose
linear/WGL search is re-implemented here with CPU and TPU backends.
"""

from .base import Checker, compose, VALID, INVALID, UNKNOWN  # noqa: F401
from .wgl_cpu import check_encoded_cpu, CpuCheckResult  # noqa: F401
from .linearizable import LinearizableChecker, check_histories  # noqa: F401
from .independent import (  # noqa: F401
    IndependentChecker,
    IndependentLinearizable,
    split_by_key,
)
from .stats import StatsChecker, UnhandledExceptionsChecker  # noqa: F401
from .perf import PerfChecker  # noqa: F401
from .timeline import TimelineChecker  # noqa: F401

"""Per-shape-bucket autotuner: measured, fingerprint-keyed plan selection.

The kernel IR (ops/kernel_ir.py) made every execution knob uniform
across kernel families; this module is what that uniformity unlocks —
the PR 6 tentpole's second half. Every tuning knob used to be a global
default (`JGRAFT_SCAN_CHUNK`, the macro payload cap, the
`JGRAFT_GROUP_DEVICES` fan-out) even though the right value is a
per-shape decision: a 16-row window group drowns in 8-way shard_map
rendezvous that a 1000-row group amortizes, and a short-event group
pays chunk-boundary flag syncs that buy it nothing. The autotuner picks
``{family, scan_chunk, macro_payload_cap, mesh_fanout}`` per SHAPE
BUCKET from short measured in-process samples, and persists the winning
plan so later processes load instead of re-measure.

Measurement discipline (the repo's hard-won rule — BENCH_r05 → PR 3
drift notes): cross-process numbers measure the host's mood, not the
machine, so every candidate is sampled IN-PROCESS, interleaved
(candidate order rotates inside each rep like scripts/ab_macro.py), on
a row-sample of the actual batch, with one untimed warm-up rep
absorbing XLA compiles. Sample runs go through the very launch path the
plan will drive (`checker/schedule.run_chunked` with
``record_stats=False``) so the measured config IS the applied config.

Persistence: ``store/autotune/<host-fingerprint>/<bucket>.json``
(JGRAFT_AUTOTUNE_STORE overrides the root). The fingerprint hashes the
STABLE host identity — cpu count, backend platform, device count,
jax/jaxlib versions — deliberately excluding load averages: a busy host
should not fork the plan store, but a toolchain swap or the r05→r06
~2.9× host change MUST. A stale or foreign fingerprint, a corrupt file,
or an unknown schema version all mean "re-measure, never silently
mis-tune".

Soundness: every candidate is a launch-shape configuration of the SAME
kernels — chunk size, payload cap and fan-out never change which events
are scanned or in what order beyond what the chunked-vs-monolithic
equivalence already covers — so verdicts are bitwise-identical tuned vs
default (pinned by tests/test_autotune.py and scripts/ab_autotune.py).
``JGRAFT_AUTOTUNE=0`` disables consultation entirely and restores
today's exact behavior.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
import time
from dataclasses import asdict, dataclass
from pathlib import Path
from typing import Callable, List, Optional, Sequence

import numpy as np

from ..history.packing import (MACRO_MAX_OPENS, bucket_rows,
                               macro_events_on, pack_batch,
                               pack_macro_batch)
from ..platform import env_float, env_int, env_str

_log = logging.getLogger(__name__)

#: Plan-file schema version; unknown versions are re-measured.
PLAN_VERSION = 1

#: Default plan-store root (gitignored alongside the test stores).
DEFAULT_STORE = "store/autotune"


def autotune_on() -> bool:
    """Whether plans are consulted/measured at all. Default ON; the
    measurement work-gates below keep small batches on the untuned
    path, so tiny runs behave exactly as before either way.
    JGRAFT_AUTOTUNE=0 restores today's behavior bit for bit. Parsed
    defensively (platform.env_int): garbage warns and keeps the
    default."""
    return env_int("JGRAFT_AUTOTUNE", 1, minimum=0) != 0


def sample_reps() -> int:
    """Timed reps per candidate (after one untimed warm-up rep).
    More reps harden the pick against host jitter at measurement
    cost."""
    return env_int("JGRAFT_AUTOTUNE_SAMPLES", 2, minimum=1)


def min_rows() -> int:
    """Work gate: groups with fewer rows than this never trigger a
    measurement (loading a persisted plan is always allowed) — the
    sample cost cannot amortize."""
    return env_int("JGRAFT_AUTOTUNE_MIN_ROWS", 64, minimum=1)


def min_cells() -> int:
    """Second work gate: rows × events must reach this many scanned
    cells before a measurement triggers."""
    return env_int("JGRAFT_AUTOTUNE_MIN_CELLS", 1 << 16, minimum=1)


def sample_rows_cap() -> int:
    """Rows per candidate sample run. The sample must stay
    representative of the LAUNCH shape the plan will drive — fan-out
    cost scales with rows-per-device, so an 8-device candidate sampled
    at 16 rows (2/device) mis-ranks against the full batch; 64 keeps
    ≥8 rows/device on the widest fan-out this repo ships."""
    return env_int("JGRAFT_AUTOTUNE_SAMPLE_ROWS", 64, minimum=1)


def store_root() -> Path:
    """Plan-store root; JGRAFT_AUTOTUNE_STORE overrides (defensively:
    a blank value keeps the default rather than writing to cwd)."""
    raw = os.environ.get("JGRAFT_AUTOTUNE_STORE", "")
    raw = raw.strip() if raw else ""
    return Path(raw) if raw else Path(DEFAULT_STORE)


# --------------------------------------------------------- fingerprint


def fingerprint_info() -> dict:
    """The STABLE host identity a plan is valid for. Excludes load
    averages on purpose (see module docstring)."""
    info = {"cpu_count": os.cpu_count()}
    try:
        import jax

        info["platform"] = jax.default_backend()
        info["devices"] = len(jax.devices())
        info["jax"] = jax.__version__
    except Exception:  # noqa: BLE001 — fingerprinting must never raise
        info["platform"] = "?"
    try:
        import jaxlib

        info["jaxlib"] = jaxlib.__version__
    except Exception:  # noqa: BLE001
        info["jaxlib"] = "?"
    return info


def host_fingerprint() -> str:
    """Short stable hash of `fingerprint_info` — the plan-store
    directory key. A host change (r05→r06-style drift: different
    cpu_count, toolchain, platform) lands in a different directory, so
    stale tunings are never silently applied."""
    raw = json.dumps(fingerprint_info(), sort_keys=True)
    return hashlib.sha256(raw.encode()).hexdigest()[:16]


# ---------------------------------------------------------------- plans


@dataclass(frozen=True)
class TunedPlan:
    """One bucket's execution plan.

    family:      kernel family tag the plan was measured for ("dense",
                 "dense-mask", "sort") — recorded for reporting and as
                 a guard: a plan never applies across families.
    scan_chunk:  chunk size for the wavefront launch; 0 = one
                 whole-schedule span (the monolithic reference shape).
    macro_p:     macro payload cap for pack_macro_batch; 0 = the legacy
                 one-event-per-step stream.
    mesh_fanout: devices the launch fans out over (outer-bounded by
                 JGRAFT_GROUP_DEVICES inside chunk_sharding); 1 =
                 single-device.
    """

    family: str
    scan_chunk: int
    macro_p: int
    mesh_fanout: int


def default_plan(family: str) -> TunedPlan:
    """Today's global defaults, as a plan — the baseline candidate
    every measurement must beat."""
    from ..parallel.mesh import chunk_sharding
    from .schedule import scan_chunk

    sharding = chunk_sharding()
    fan = int(getattr(sharding, "mesh", None).size) if sharding is not None \
        else 1
    return TunedPlan(family=family, scan_chunk=scan_chunk(),
                     macro_p=MACRO_MAX_OPENS if macro_events_on() else 0,
                     mesh_fanout=fan)


def bucket_signature(family: str, n_slots: int, n_states: int,
                     n_rows: int, n_events: int) -> tuple:
    """The shape bucket a plan is keyed by: kernel family, exact
    window/state shape (they pick the compiled kernel), the
    pow2+midpoint row/event buckets (they pick the launch shape — the
    same series `pad_batch_bucketed` pads to, so two batches that share
    compiled shapes share a plan), and the macro-stream mode: plans
    measured under the macro stream must never leak into a
    JGRAFT_MACRO_EVENTS=0 ablation run (the macro A/B must stay a pure
    stream comparison)."""
    return (family, int(n_slots), int(n_states),
            bucket_rows(max(int(n_rows), 1)),
            bucket_rows(max(int(n_events), 1), 32),
            int(macro_events_on()))


def _sig_name(sig: tuple) -> str:
    fam, w, s, b, e, macro = sig
    return f"{fam}-w{w}-s{s}-b{b}-e{e}-m{macro}.json"


# ------------------------------------------------------ store + counters

_LOCK = threading.Lock()
_MISS = object()          # negative-cache sentinel (see plan_for)
_MEM: dict = {}           # sig -> TunedPlan | _MISS (this process)
_APPLIED: List[dict] = []  # bounded log of applied plans (service stamps)
_APPLIED_SEQ = 0           # monotone id of the last applied entry
_COUNTERS = {"plans_loaded": 0, "plans_measured": 0, "plan_misses": 0}


def snapshot_counters() -> dict:
    with _LOCK:
        return dict(_COUNTERS)


def consume_counters() -> dict:
    """Return and reset the counters (bench.py reads one rep's worth)."""
    with _LOCK:
        out = dict(_COUNTERS)
        for k in _COUNTERS:
            _COUNTERS[k] = 0
        return out


def applied_log() -> List[dict]:
    """Bounded log of {seq, signature, plan, source} entries, in
    application order (the recording thread id stays internal)."""
    with _LOCK:
        return [{k: v for k, v in e.items() if k != "thread"}
                for e in _APPLIED]


def applied_seq() -> int:
    """Monotone id of the most recent applied-plan entry. Callers
    attributing plans to a span (graftd's scheduler) snapshot this
    BEFORE the work and read `applied_since` after — slicing the
    bounded log by LENGTH would break the moment trimming starts (the
    length pins at the bound and the slice goes permanently empty)."""
    with _LOCK:
        return _APPLIED_SEQ


def applied_since(seq: int, thread_id: Optional[int] = None) -> List[dict]:
    """Entries applied after `seq` that are still inside the bounded
    log (a span applying more than the bound keeps the newest).
    `thread_id` restricts to plans applied BY that thread — graftd's
    concurrent shard executors (ISSUE 7) each stamp only the plans
    their own batch's launch consulted, not a neighbor shard's."""
    with _LOCK:
        return [{k: v for k, v in e.items() if k != "thread"}
                for e in _APPLIED
                if e["seq"] > seq
                and (thread_id is None or e.get("thread") == thread_id)]


def _record_applied(sig: tuple, plan: TunedPlan, source: str) -> None:
    global _APPLIED_SEQ
    with _LOCK:
        _APPLIED_SEQ += 1
        _APPLIED.append({"seq": _APPLIED_SEQ, "signature": list(sig),
                         "plan": asdict(plan), "source": source,
                         "thread": threading.get_ident()})
        del _APPLIED[:-256]


def reset_for_tests() -> None:
    """Drop the in-memory plan cache + counters (tests simulate fresh
    processes)."""
    with _LOCK:
        _MEM.clear()
        _APPLIED.clear()
        _LINFP_MEM.clear()
        _CYCLE_MEM.clear()
        for k in _COUNTERS:
            _COUNTERS[k] = 0


def _plan_path(sig: tuple) -> Path:
    return store_root() / host_fingerprint() / _sig_name(sig)


def plan_for(sig: tuple) -> Optional[TunedPlan]:
    """Look a bucket's plan up: in-memory first, then the fingerprint
    directory on disk. Corrupt files, schema drift, and fingerprint
    mismatch (an operator copying plan files across hosts) all return
    None — re-measure, never silently mis-tune.

    Misses are negative-cached in memory: a long-lived daemon consults
    per window group and per ladder rung on EVERY batch, and a
    below-work-gate bucket would otherwise pay a disk stat per consult
    forever. The sentinel is replaced by `save_plan` when this process
    measures; a plan persisted by a DIFFERENT process mid-flight is
    picked up on the next process start (acceptable — cross-process
    plan sharing is a restart-time optimization, not a liveness
    contract)."""
    with _LOCK:
        plan = _MEM.get(sig)
    if plan is _MISS:
        return None
    if plan is not None:
        _bump("plans_loaded")
        _record_applied(sig, plan, "memory")
        return plan
    path = _plan_path(sig)
    try:
        raw = json.loads(path.read_text())
    except FileNotFoundError:
        return _miss(sig)
    except (OSError, json.JSONDecodeError, UnicodeDecodeError) as e:
        _log.warning("autotune: unreadable plan %s (%s: %s) — "
                     "re-measuring", path, type(e).__name__, e)
        return _miss(sig)
    try:
        if raw.get("version") != PLAN_VERSION:
            raise ValueError(f"schema version {raw.get('version')!r}")
        if raw.get("fingerprint") != host_fingerprint():
            raise ValueError("host fingerprint mismatch")
        if raw.get("signature") != list(sig):
            raise ValueError("bucket signature mismatch")
        plan = TunedPlan(**{k: raw["plan"][k] for k in
                            ("family", "scan_chunk", "macro_p",
                             "mesh_fanout")})
    except (KeyError, TypeError, ValueError, AttributeError) as e:
        _log.warning("autotune: stale/corrupt plan %s (%s: %s) — "
                     "re-measuring", path, type(e).__name__, e)
        return _miss(sig)
    with _LOCK:
        _MEM[sig] = plan
    _bump("plans_loaded")
    _record_applied(sig, plan, "disk")
    return plan


def _miss(sig: tuple):
    with _LOCK:
        _MEM[sig] = _MISS
        _COUNTERS["plan_misses"] += 1
    return None


def _bump(key: str) -> None:
    with _LOCK:
        _COUNTERS[key] += 1


def save_plan(sig: tuple, plan: TunedPlan, samples: dict) -> None:
    """Persist a measured plan (atomic tmp+rename; persistence failures
    warn and keep the in-memory plan — a read-only store must not break
    checking)."""
    with _LOCK:
        _MEM[sig] = plan
    path = _plan_path(sig)
    payload = {
        "version": PLAN_VERSION,
        "fingerprint": host_fingerprint(),
        "fingerprint_info": fingerprint_info(),
        "signature": list(sig),
        "plan": asdict(plan),
        "samples": samples,
        "created_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    try:
        path.parent.mkdir(parents=True, exist_ok=True)
        tmp = path.with_suffix(".tmp")
        tmp.write_text(json.dumps(payload, indent=2))
        os.replace(tmp, path)
    except OSError as e:
        _log.warning("autotune: could not persist plan %s (%s: %s)",
                     path, type(e).__name__, e)


# ----------------------------------------------------------- measurement


def resolve_plan(sig: tuple, candidates: Sequence[TunedPlan],
                 measure: Callable[[TunedPlan], float]) -> TunedPlan:
    """Measure `candidates` interleaved (ab_macro.py discipline: one
    untimed warm-up rep per candidate absorbs XLA compiles, then
    `sample_reps` timed rounds with the candidate order rotating so
    slow host drift cancels instead of biasing one candidate), pick the
    best-of-min, persist, and return. The caller has already missed
    `plan_for`."""
    times: dict = {c: [] for c in candidates}
    for c in candidates:     # warm-up: compile every candidate's shapes
        measure(c)
    reps = sample_reps()
    for rep in range(reps):
        order = list(candidates)[rep % len(candidates):] + \
            list(candidates)[:rep % len(candidates)]
        for c in order:
            times[c].append(measure(c))
    best = min(candidates, key=lambda c: min(times[c]))
    samples = {json.dumps(asdict(c)): [round(t, 5) for t in ts]
               for c, ts in times.items()}
    save_plan(sig, best, samples)
    _bump("plans_measured")
    _record_applied(sig, best, "measured")
    return best


def pack_group(encs: Sequence, tuned: Optional[TunedPlan]) -> dict:
    """Pack one group's encodings under a plan's macro payload cap —
    or under today's defaults when no plan applies (tuned None). The
    JGRAFT_MACRO_EVENTS=0 ablation is absolute: a persisted macro plan
    must never re-enable the macro stream under it."""
    if not macro_events_on():
        return pack_batch(encs)
    if tuned is None:
        return pack_macro_batch(encs)
    if tuned.macro_p <= 0:
        return pack_batch(encs)
    return pack_macro_batch(encs, cap=tuned.macro_p)


def _chunk_candidates(default_chunk: int, e_sched: int) -> List[int]:
    """Chunk sizes worth sampling for a schedule of `e_sched` events:
    the global default, its neighbors, and 0 (one whole-schedule span).
    Values ≥ the schedule collapse into 0's shape and are dropped."""
    cands = [default_chunk, default_chunk * 2, 0]
    out: List[int] = []
    for c in cands:
        if c >= max(e_sched, 1):
            c = 0
        if c not in out:
            out.append(c)
    return out


def _fanout_candidates() -> List[int]:
    """Fan-out widths worth sampling: the full mesh, a 2-device mesh,
    and single-device. On hosts where devices are virtual (pin_cpu's
    8 vdevs over 2 cores) the snug meshes routinely win — the
    per-launch partition rendezvous scales with device count."""
    from ..parallel.mesh import chunk_sharding

    sharding = chunk_sharding()
    full = int(sharding.mesh.size) if sharding is not None else 1
    out = [full]
    for n in (max(full // 2, 2), 2, 1):
        if n < full and n not in out:
            out.append(n)
    return out


def _macro_candidates() -> List[int]:
    """Macro payload caps worth sampling. The macro stream's 1.8× win
    is established, so the legacy stream (0) is only re-sampled via the
    cap ladder's smallest rung — a narrower cap trades more rows for
    narrower ones, which can win on the host."""
    if not macro_events_on():
        return [0]
    return [MACRO_MAX_OPENS, 4]


def tuned_group_plan(model, plan, encs: Sequence) -> Optional[TunedPlan]:
    """Consult (and, for large-enough groups, measure) the plan for one
    dense window group. `plan` is the group's ops.dense_scan.DensePlan;
    `encs` the group's encodings in plan row order. Returns None —
    today's exact behavior — when autotuning is off, the group is LONG
    (the merged-cluster policies are separately measured), or the group
    is below the work gates with no persisted plan."""
    if not autotune_on() or not encs:
        return None
    from ..ops.dense_scan import MERGE_MAX_EVENTS

    e_max = max(e.n_events for e in encs)
    if e_max > MERGE_MAX_EVENTS:
        return None
    sig = bucket_signature(plan.kernel_tag, plan.n_slots, plan.n_states,
                           len(encs), e_max)
    found = plan_for(sig)
    if found is not None:
        return found
    if len(encs) < min_rows() or len(encs) * e_max < min_cells():
        return None
    k = min(len(encs), sample_rows_cap())
    sample = list(encs[:k])
    val_of = np.asarray(plan.val_of[:k])
    e_sched = bucket_rows(e_max, 32)

    def measure(cand: TunedPlan) -> float:
        return _run_dense_sample(model, plan, sample, val_of, cand)

    candidates = _coordinate_candidates(plan.kernel_tag, e_sched)
    return resolve_plan(sig, candidates, measure)


def _coordinate_candidates(family: str, e_sched: int) -> List[TunedPlan]:
    """The candidate grid, kept deliberately small (each distinct
    launch shape is an XLA compile during measurement): chunk ladder ×
    {default fan-out} plus fan-out ladder × {default chunk} plus macro
    ladder × {default chunk+fanout} — a star around the default rather
    than the full cross product."""
    base = default_plan(family)
    out: List[TunedPlan] = [base]

    def add(**kw):
        c = TunedPlan(**{**asdict(base), **kw})
        if c not in out:
            out.append(c)

    for chunk in _chunk_candidates(base.scan_chunk or 128, e_sched):
        add(scan_chunk=chunk)
    for fan in _fanout_candidates():
        add(mesh_fanout=fan)
    for p in _macro_candidates():
        add(macro_p=p)
    return out


def _run_dense_sample(model, plan, sample: Sequence, val_of: np.ndarray,
                      cand: TunedPlan) -> float:
    """One timed sample run of a dense group candidate, through the
    exact launch path the plan will drive (build_dense_launches'
    placement mapping, run_chunked driver, stats suppressed)."""
    from ..ops.dense_scan import make_dense_chunk_checker
    from ..parallel.mesh import chunk_sharding
    from .schedule import ChunkLaunch, run_chunked

    batch = pack_group(sample, cand)
    e_len = batch["events"].shape[1]
    e_sched = bucket_rows(e_len, 32)
    sharding = chunk_sharding(cand.mesh_fanout)
    init_fn, step_fn = make_dense_chunk_checker(
        model, plan.kind, plan.n_slots, plan.n_states,
        mesh=getattr(sharding, "mesh", None),
        macro_p=batch.get("macro_p"))
    chunk = cand.scan_chunk or max(e_sched, 1)
    launch = ChunkLaunch(
        events=batch["events"], n_events=batch["n_events"],
        init_fn=init_fn, step_fn=step_fn, val_of=val_of,
        e_sched=e_sched, device=sharding, tag="autotune-sample",
        chunk=chunk)
    t0 = time.perf_counter()
    run_chunked([launch], chunk=chunk, record_stats=False)
    return time.perf_counter() - t0


def tuned_sort_plan(model, encs: Sequence, n_configs: int,
                    n_slots: int) -> Optional[TunedPlan]:
    """Sort-ladder twin of `tuned_group_plan` for one capacity rung;
    the rung's frontier capacity rides the signature's state slot (it
    picks the compiled kernel exactly like S does for the dense
    family).

    The sort rung's base plan pins `mesh_fanout=1` — TODAY'S behavior:
    unlike the dense groups, the pre-autotune sort rung never got the
    PR 3 mesh fan-out (single-device vmap). That makes fan-out the
    rung's headline candidate dimension: on the 8-vdev host mesh a
    fanned-out sort rung measured 1.84× over the single-device default
    at the wide-domain register shape (2026-08-04, this host) — the
    kind of per-bucket mis-calibration this module exists to find."""
    if not autotune_on() or not encs:
        return None
    e_max = max(e.n_events for e in encs)
    sig = bucket_signature("sort", n_slots, n_configs, len(encs), e_max)
    found = plan_for(sig)
    if found is not None:
        return found
    if len(encs) < min_rows() or len(encs) * e_max < min_cells():
        return None
    sample = list(encs[:min(len(encs), sample_rows_cap())])
    e_sched = bucket_rows(e_max, 32)

    def measure(cand: TunedPlan) -> float:
        return _run_sort_sample(model, n_configs, n_slots, sample, cand)

    base = TunedPlan(**{**asdict(default_plan("sort")), "mesh_fanout": 1})
    candidates: List[TunedPlan] = [base]
    for chunk in _chunk_candidates(base.scan_chunk or 128, e_sched):
        c = TunedPlan(**{**asdict(base), "scan_chunk": chunk})
        if c not in candidates:
            candidates.append(c)
    for fan in _fanout_candidates():
        c = TunedPlan(**{**asdict(base), "mesh_fanout": fan})
        if c not in candidates:
            candidates.append(c)
    for p in _macro_candidates():
        c = TunedPlan(**{**asdict(base), "macro_p": p})
        if c not in candidates:
            candidates.append(c)
    return resolve_plan(sig, candidates, measure)


# ------------------------------------- lin fast-path gating (ISSUE 14)
# The linearizable-rung pre-kernel certify pass (checker/linearizable
# `lin_fastpath_pass`) has a measured worst case: a batch whose rows the
# host certifier cannot decide pays the host scan AND the kernel. The
# autotuner therefore grows a `lin_fastpath` dimension: per
# (model family, event shape-bucket) it accumulates hit-rate and
# marginal-wall samples — persisted in the SAME host-fingerprinted
# store as the launch plans (a host change invalidates certify-speed
# observations exactly like chunk timings) — and `lin_fastpath_route`
# answers whether a bucket should try the host certifier first or go
# kernel-first. Gating only ever affects ROUTING, never verdicts
# (undecided rows always reach the kernels), and is part of the
# measured autotuner: with JGRAFT_AUTOTUNE=0 the fast path always
# tries (flag-only behavior, no host-state dependence — what the
# deterministic test environment pins).

#: lin-fastpath record schema version; unknown versions re-observe.
LINFP_VERSION = 1

_LINFP_MEM: dict = {}   # sig -> {"rows", "hits", "certify_wall_s"}


def lin_fastpath_min_hit() -> float:
    """Hit-rate floor below which a measured bucket routes kernel-first
    (JGRAFT_LIN_FASTPATH_MIN_HIT, default 0.05 — the ~5% worst-case
    overhead bound the acceptance A/B pins; defensive parse)."""
    return env_float("JGRAFT_LIN_FASTPATH_MIN_HIT", 0.05, minimum=0.0)


def lin_fastpath_min_obs() -> int:
    """Rows a bucket must have been observed over before the hit-rate
    gate may route it kernel-first (JGRAFT_LIN_FASTPATH_MIN_OBS,
    default 64): trying IS measuring, so unknown buckets always try."""
    return env_int("JGRAFT_LIN_FASTPATH_MIN_OBS", 64, minimum=1)


def lin_fastpath_sig(family: str, n_events: int) -> tuple:
    """Gating bucket: model family plus the pow2+midpoint event bucket
    (the same floor-32 series the launch shapes pad to). Window/state
    shape is deliberately absent — certify cost scales with E·W but the
    hit-rate is a property of the WORKLOAD family, and fragmenting the
    observations per window would starve the gate of samples."""
    return ("linfp", str(family), bucket_rows(max(int(n_events), 1), 32))


def _linfp_path(sig: tuple) -> Path:
    return store_root() / host_fingerprint() / \
        f"linfp-{sig[1]}-e{sig[2]}.json"


def linfp_shared_dir() -> Optional[Path]:
    """Shared gate-store directory (ISSUE 18 satellite): when
    JGRAFT_LINFP_DIR (fallback: the cluster rendezvous dir,
    JGRAFT_SERVICE_CLUSTER_DIR) names a directory every replica can
    reach, lin-fastpath gate records replicate through
    ``<dir>/linfp/`` so the fast path can re-enable inside distributed
    wavefronts: every rank routes off the same published snapshot
    instead of its private observation history. Unset → None (gating
    stays host-local, wavefronts stay kernel-first)."""
    raw = env_str("JGRAFT_LINFP_DIR", "").strip()
    if not raw:
        raw = (env_str("JGRAFT_SERVICE_CLUSTER_DIR") or "").strip()
    if not raw:
        return None
    return Path(raw) / "linfp"


def _linfp_shared_path(sig: tuple) -> Optional[Path]:
    d = linfp_shared_dir()
    if d is None:
        return None
    return d / f"linfp-{sig[1]}-e{sig[2]}.json"


def _load_linfp(path: Path, sig: tuple, require_host: bool) -> \
        Optional[dict]:
    """Parse one gate record, or None. Shared records skip the
    host-fingerprint check: the hit-RATE the gate routes on is a
    property of the workload family, not the host (the wall figures
    travel along but are advisory) — while host-local records keep the
    strict check so a toolchain swap re-observes, exactly like plans."""
    try:
        raw = json.loads(path.read_text())
        if (raw.get("version") == LINFP_VERSION
                and raw.get("signature") == list(sig)
                and (not require_host
                     or raw.get("fingerprint") == host_fingerprint())):
            return {"rows": int(raw["rows"]), "hits": int(raw["hits"]),
                    "certify_wall_s": float(raw["certify_wall_s"])}
        _log.warning("autotune: stale lin-fastpath record %s — "
                     "re-observing", path)
    except FileNotFoundError:
        pass
    except (OSError, json.JSONDecodeError, UnicodeDecodeError,
            KeyError, TypeError, ValueError) as e:
        _log.warning("autotune: unreadable lin-fastpath record %s "
                     "(%s: %s) — re-observing", path, type(e).__name__, e)
    return None


def _linfp_record(sig: tuple) -> dict:
    """The bucket's in-memory record, seeded on first touch from the
    fingerprint store — or, when the local file is absent/stale, from
    the shared gate dir, which is how a fresh replica inherits the
    cluster's gate history instead of paying min_obs rows of
    re-observation. Corrupt/stale/foreign files mean 'start fresh,
    never silently mis-gate' — same stance as `plan_for`."""
    with _LOCK:
        rec = _LINFP_MEM.get(sig)
        if rec is not None:
            return rec
    fresh = _load_linfp(_linfp_path(sig), sig, require_host=True)
    if fresh is None:
        shared = _linfp_shared_path(sig)
        if shared is not None:
            fresh = _load_linfp(shared, sig, require_host=False)
    if fresh is None:
        fresh = {"rows": 0, "hits": 0, "certify_wall_s": 0.0}
    with _LOCK:
        rec = _LINFP_MEM.setdefault(sig, fresh)
    return rec


def lin_fastpath_route(sig: tuple) -> bool:
    """True → run the host certifier first for this bucket; False →
    the measured hit-rate says kernel-first. Routing only: a gated
    bucket's rows take the ordinary kernel ladder unchanged."""
    if not autotune_on():
        return True
    rec = _linfp_record(sig)
    with _LOCK:
        rows, hits = rec["rows"], rec["hits"]
    if rows < lin_fastpath_min_obs():
        return True
    return hits / rows >= lin_fastpath_min_hit()


def lin_fastpath_observe(sig: tuple, rows: int, hits: int,
                         wall_s: float) -> None:
    """Fold one batch's certify outcome into the bucket's record and
    persist it (atomic tmp+rename, best-effort — a read-only store
    degrades gating to in-memory, never checking)."""
    if rows <= 0 or not autotune_on():
        return
    rec = _linfp_record(sig)
    with _LOCK:
        rec["rows"] += int(rows)
        rec["hits"] += int(hits)
        rec["certify_wall_s"] += float(wall_s)
        payload = {
            "version": LINFP_VERSION,
            "fingerprint": host_fingerprint(),
            "fingerprint_info": fingerprint_info(),
            "signature": list(sig),
            "rows": rec["rows"],
            "hits": rec["hits"],
            "certify_wall_s": round(rec["certify_wall_s"], 6),
            # the marginal-wall sample an operator reads the gate by
            "certify_wall_per_row_s": round(
                rec["certify_wall_s"] / max(rec["rows"], 1), 6),
            "hit_rate": round(rec["hits"] / max(rec["rows"], 1), 4),
            "updated_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                         time.gmtime()),
        }
    # publish locally AND (when configured) into the shared gate dir,
    # so sibling replicas inherit the observation. Shared writes race
    # last-writer-wins across replicas; each writer's record carries a
    # complete, internally consistent observation history, so whichever
    # lands is a valid gate input (per-pid tmp names keep the renames
    # atomic and non-colliding).
    targets = [_linfp_path(sig)]
    shared = _linfp_shared_path(sig)
    if shared is not None:
        targets.append(shared)
    for path in targets:
        try:
            path.parent.mkdir(parents=True, exist_ok=True)
            tmp = path.with_suffix(f".{os.getpid()}.tmp")
            tmp.write_text(json.dumps(payload, indent=2))
            os.replace(tmp, path)
        except OSError as e:
            _log.warning("autotune: could not persist lin-fastpath "
                         "record %s (%s: %s)", path, type(e).__name__, e)


# ------------------------------------------------- cycle-tier arm store
# ISSUE 19: the exact cycle tier has two routing dimensions per node
# bucket — condense-vs-direct (host Tarjan pre-pass or straight to the
# detector) and kernel-vs-DFS (batched closure launch or host 3-color
# DFS). Host-CPU thresholds are explicitly re-calibratable (ROADMAP
# item 5), so the choice is measured per bucket with the same
# plan-store discipline as the launch plans: fingerprint-keyed JSON,
# version/signature checks, in-memory negative cache, interleaved
# rotated best-of-min measurement. Arm choice is ROUTING ONLY — every
# arm is verdict-identical (differentially pinned in
# tests/test_cycle_tiled.py), so a stale or foreign record can only
# cost time, never answers.

#: cycle-arm record schema version; unknown versions re-measure.
CYCLE_ARM_VERSION = 1

#: Measurable arms, in deterministic measurement order: "condense" =
#: host Tarjan SCC pre-pass (detection IS the pre-pass), "dfs" = direct
#: host 3-color DFS, "kernel" = direct batched closure launch.
CYCLE_ARMS = ("condense", "dfs", "kernel")

_CYCLE_MEM: dict = {}   # sig -> arm str | _MISS


def cycle_arm_sig(n_bucket: int) -> tuple:
    """Arm bucket: the pow2+midpoint node bucket alone. The arm
    tradeoff is a property of graph size and host-vs-device matmul
    cost, not of the model family — fragmenting per family would
    starve small buckets of measurements."""
    return ("cycle-arm", int(n_bucket))


def _cycle_arm_path(sig: tuple) -> Path:
    return store_root() / host_fingerprint() / f"cycle-arm-n{sig[1]}.json"


def cycle_arm_for(sig: tuple) -> Optional[str]:
    """The bucket's measured arm: memory, then the fingerprint store.
    Same failure stance as `plan_for` — corrupt/stale/foreign records
    return None (re-measure, never silently mis-route), misses are
    negative-cached so per-batch consults stay disk-free."""
    with _LOCK:
        arm = _CYCLE_MEM.get(sig)
    if arm is _MISS:
        return None
    if arm is not None:
        _bump("plans_loaded")
        return arm
    path = _cycle_arm_path(sig)
    try:
        raw = json.loads(path.read_text())
    except FileNotFoundError:
        return _cycle_miss(sig)
    except (OSError, json.JSONDecodeError, UnicodeDecodeError) as e:
        _log.warning("autotune: unreadable cycle-arm record %s (%s: %s)"
                     " — re-measuring", path, type(e).__name__, e)
        return _cycle_miss(sig)
    arm = raw.get("arm")
    if (raw.get("version") != CYCLE_ARM_VERSION
            or raw.get("fingerprint") != host_fingerprint()
            or raw.get("signature") != list(sig)
            or arm not in CYCLE_ARMS):
        _log.warning("autotune: stale/corrupt cycle-arm record %s — "
                     "re-measuring", path)
        return _cycle_miss(sig)
    with _LOCK:
        _CYCLE_MEM[sig] = arm
    _bump("plans_loaded")
    return arm


def _cycle_miss(sig: tuple):
    with _LOCK:
        _CYCLE_MEM[sig] = _MISS
        _COUNTERS["plan_misses"] += 1
    return None


def save_cycle_arm(sig: tuple, arm: str, samples: dict) -> None:
    """Persist a measured arm (atomic tmp+rename; persistence failures
    warn and keep the in-memory arm)."""
    with _LOCK:
        _CYCLE_MEM[sig] = arm
    path = _cycle_arm_path(sig)
    payload = {
        "version": CYCLE_ARM_VERSION,
        "fingerprint": host_fingerprint(),
        "fingerprint_info": fingerprint_info(),
        "signature": list(sig),
        "arm": arm,
        "samples": samples,
        "created_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    try:
        path.parent.mkdir(parents=True, exist_ok=True)
        tmp = path.with_suffix(f".{os.getpid()}.tmp")
        tmp.write_text(json.dumps(payload, indent=2))
        os.replace(tmp, path)
    except OSError as e:
        _log.warning("autotune: could not persist cycle-arm record %s "
                     "(%s: %s)", path, type(e).__name__, e)


def resolve_cycle_arm(sig: tuple,
                      measures: "dict[str, Callable[[], float]]") -> str:
    """Measure the available arms interleaved (one untimed warm-up rep
    each absorbs XLA compiles, then `sample_reps` rounds with rotating
    order — the resolve_plan discipline verbatim), pick best-of-min,
    persist, return. `measures` maps arm name → zero-arg wall-seconds
    measurement over the SAME batch of graphs; the caller asserts
    verdict identity across arms before trusting any timing (the
    scripts/ab_cycle.py stance, applied in-process)."""
    arms = [a for a in CYCLE_ARMS if a in measures]
    times: dict = {a: [] for a in arms}
    for a in arms:
        measures[a]()
    reps = sample_reps()
    for rep in range(reps):
        order = arms[rep % len(arms):] + arms[:rep % len(arms)]
        for a in order:
            times[a].append(measures[a]())
    best = min(arms, key=lambda a: min(times[a]))
    samples = {a: [round(t, 6) for t in ts] for a, ts in times.items()}
    save_cycle_arm(sig, best, samples)
    _bump("plans_measured")
    return best


def sort_rung_sharding(tuned: Optional[TunedPlan]):
    """The sort rung's launch placement under a plan: None (today's
    single-device rung) without a plan or at fanout ≤ 1, else the
    capped batch-axis sharding."""
    if tuned is None or tuned.mesh_fanout <= 1:
        return None
    from ..parallel.mesh import chunk_sharding

    return chunk_sharding(tuned.mesh_fanout)


def _run_sort_sample(model, n_configs: int, n_slots: int,
                     sample: Sequence, cand: TunedPlan) -> float:
    from ..ops.linear_scan import make_sort_chunk_checker
    from .schedule import ChunkLaunch, run_chunked

    batch = pack_group(sample, cand)
    e_sched = bucket_rows(batch["events"].shape[1], 32)
    sharding = sort_rung_sharding(cand)
    init_fn, step_fn = make_sort_chunk_checker(
        model, n_configs, n_slots, mesh=getattr(sharding, "mesh", None),
        macro_p=batch.get("macro_p"))
    chunk = cand.scan_chunk or max(e_sched, 1)
    launch = ChunkLaunch(
        events=batch["events"], n_events=batch["n_events"],
        init_fn=init_fn, step_fn=step_fn, e_sched=e_sched,
        device=sharding, tag="autotune-sample", chunk=chunk)
    t0 = time.perf_counter()
    run_chunked([launch], chunk=chunk, record_stats=False)
    return time.perf_counter() - t0

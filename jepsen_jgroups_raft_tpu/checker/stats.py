"""Sanity checkers: op-count stats and unhandled exceptions.

Equivalents of jepsen checker/stats and checker/unhandled-exceptions
(reference raft.clj:75-76). Stats is valid iff every op kind that ran has
at least one ok (jepsen's rule); exceptions reports error kinds seen.
"""

from __future__ import annotations

from collections import Counter as TallyCounter

from ..history.ops import FAIL, INFO, INVOKE, OK, History
from .base import Checker


class StatsChecker(Checker):
    def check(self, test, history, opts=None) -> dict:
        if not isinstance(history, History):
            history = History(history)
        by_f: dict = {}
        for op in history.client_ops():
            if op.type == INVOKE:
                continue
            t = by_f.setdefault(op.f, TallyCounter())
            t[op.type] += 1
        result_by_f = {
            f: {
                "count": sum(t.values()),
                "ok-count": t[OK],
                "fail-count": t[FAIL],
                "info-count": t[INFO],
                "valid?": t[OK] > 0,
            }
            for f, t in by_f.items()
        }
        return {
            "valid?": all(r["valid?"] for r in result_by_f.values()) or not result_by_f,
            **{str(f): r for f, r in result_by_f.items()},
        }


class UnhandledExceptionsChecker(Checker):
    """Tally error annotations on fail/info ops (the analogue of jepsen's
    unhandled-exceptions checker: surface what went wrong, never fail the
    test by itself)."""

    def check(self, test, history, opts=None) -> dict:
        if not isinstance(history, History):
            history = History(history)
        tally: TallyCounter = TallyCounter()
        for op in history:
            if op.error:
                kind = str(op.error).split(":", 1)[0]
                tally[kind] += 1
        return {"valid?": True, "error-kinds": dict(tally)}

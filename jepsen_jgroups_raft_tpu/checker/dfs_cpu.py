"""DFS-with-undo linearizability search — the second engine.

The reference RACES two different checker algorithms via
knossos.competition/analysis (:linear vs :wgl, reference
test/jepsen/jgroups/raft_test.clj:26,41,64) and takes the first finisher.
This module is this framework's second engine: the classic Wing&Gong /
knossos/porcupine depth-first search with undo and memoization — a
genuinely different search order from the frontier scan (wgl_cpu.py /
ops/linear_scan.py), which is breadth-first over configuration sets.

Why both: DFS commits to ONE linearization order at a time, so on histories
where almost any order works (the common valid case) it finishes after ~n
steps without ever materializing the configuration frontier; the frontier
scan does uniform data-parallel work regardless. Conversely, adversarial
histories can send DFS into deep backtracking that frontier dedup shrugs
off. Racing them (`algorithm="race"`, checker/linearizable.py) gets the
minimum of the two costs, like the reference's competition analysis.

Algorithm (porcupine-style, on the packed event stream of
history/packing.py — OPEN is an op's invoke point, FORCE its completion):
walk an entry list; at an OPEN entry try to linearize that op now (apply
the model step, consult the visited cache of (linearized-mask, state));
on success lift the op's OPEN and FORCE entries from the list and push an
undo record; a FORCE entry reached before its op linearized ⇒ every op
order consistent with real time has been tried for this prefix ⇒ backtrack
(undo the most recent tentative linearization, resume after it). The
visited cache makes revisits O(1): a (mask, state) pair that failed once
can never succeed later, because future legality depends only on it.
Crashed (info) ops have an OPEN but no FORCE: they are optional — eligible
for linearization forever, never forcing backtracking. Success = every
FORCE entry consumed.
"""

from __future__ import annotations

from typing import Optional

from ..history.packing import EV_FORCE, EV_OPEN, EncodedHistory
from .wgl_cpu import CpuCheckResult


class SearchBudgetExceeded(Exception):
    """DFS step budget exhausted (adversarial backtracking)."""

    def __init__(self, steps: int):
        super().__init__(f"dfs budget exceeded: {steps} steps")
        self.steps = steps


def check_encoded_dfs(
    enc: EncodedHistory,
    model,
    max_steps: Optional[int] = None,
    witness: bool = False,
) -> CpuCheckResult:
    """Run the DFS-with-undo search on one encoded history.

    max_steps bounds total loop iterations (None = unbounded); exceeding it
    raises SearchBudgetExceeded so callers can escalate/race rather than
    hang on adversarial histories.
    """

    events = enc.events
    n = enc.n_events

    # Entry list over event indices, doubly linked through arrays.
    # nxt/prv have a virtual head at index -1 (head) and tail at n.
    nxt = list(range(1, n + 1))
    prv = list(range(-1, n))
    head = 0 if n > 0 else n

    # Per-event metadata.
    op_f = events[:, 2]
    op_a = events[:, 3]
    op_b = events[:, 4]
    # For each OPEN event, the event index of its FORCE (or -1 if info).
    force_of = [-1] * n
    open_of = [-1] * n  # for each FORCE event, its OPEN's event index
    last_open_for_slot: dict = {}
    op_bit = [0] * n  # distinct bit per op (OPEN event index order)
    bit = 1
    for ei in range(n):
        et, slot = int(events[ei, 0]), int(events[ei, 1])
        if et == EV_OPEN:
            last_open_for_slot[slot] = ei
            op_bit[ei] = bit
            bit <<= 1
        elif et == EV_FORCE:
            oi = last_open_for_slot[slot]
            force_of[oi] = ei
            open_of[ei] = oi
    n_forces = sum(1 for ei in range(n) if int(events[ei, 0]) == EV_FORCE)

    def unlink(i: int) -> None:
        nonlocal head
        p, q = prv[i], nxt[i]
        if p == -1:
            head = q
        else:
            nxt[p] = q
        if q < n:
            prv[q] = p

    def relink(i: int) -> None:
        nonlocal head
        p, q = prv[i], nxt[i]
        if p == -1:
            head = i
        else:
            nxt[p] = i
        if q < n:
            prv[q] = i

    state = model.init_state()
    mask = 0
    cache = {(0, state)}
    undo: list = []  # (open_ei, prev_state) — linearization order, newest last
    remaining_forces = n_forces
    steps = 0
    furthest_block = -1  # furthest FORCE event the search ever got stuck on
                         # — "the linearizable prefix ends here", matching
                         # the frontier engine's failing-op semantics

    cur = head
    while True:
        if remaining_forces == 0:
            return CpuCheckResult(
                valid=True,
                configs_explored=len(cache),
                max_frontier=len(undo) + 1,
                witness=[int(enc.op_index[ei]) for ei, _ in undo]
                if witness else None,
            )
        steps += 1
        if max_steps is not None and steps > max_steps:
            raise SearchBudgetExceeded(steps)
        if cur >= n:
            # Walked off the tail: only un-linearizable entries remain
            # ahead, and no FORCE was hit — means remaining entries are all
            # OPENs of info ops that can't legally linearize. That's fine
            # only if no FORCE remains (handled above); otherwise the next
            # pass from head would loop, so treat like hitting a FORCE of
            # an unlinearized op: backtrack.
            et = EV_FORCE
            force_blocked_ei = None
        else:
            et = int(events[cur, 0])
            force_blocked_ei = cur
        if et == EV_OPEN:
            ei = cur
            s2, legal = model.step(state, int(op_f[ei]), int(op_a[ei]),
                                   int(op_b[ei]))
            cfg = (mask | op_bit[ei], s2)
            if legal and cfg not in cache:
                cache.add(cfg)
                undo.append((ei, state))
                state = s2
                mask |= op_bit[ei]
                fe = force_of[ei]
                unlink(ei)
                if fe >= 0:
                    unlink(fe)
                    remaining_forces -= 1
                cur = head
            else:
                cur = nxt[cur]
        else:  # FORCE (or tail): op not linearized in time — backtrack
            if force_blocked_ei is not None:
                furthest_block = max(furthest_block, force_blocked_ei)
            if not undo:
                return CpuCheckResult(
                    valid=False,
                    configs_explored=len(cache),
                    max_frontier=1,
                    failing_op_index=int(enc.op_index[furthest_block])
                    if furthest_block >= 0 else None,
                    witness=None,
                )
            ei, prev_state = undo.pop()
            fe = force_of[ei]
            if fe >= 0:
                relink(fe)
                remaining_forces += 1
            relink(ei)
            state = prev_state
            mask &= ~op_bit[ei]
            cur = nxt[ei]

"""Linearizability checker with selectable execution backend.

Equivalent of `jepsen.checker/linearizable {:model m :algorithm ...}`
(reference register.clj:106-111, counter.clj:133-137) with the north-star
addition: algorithm ``"jax"`` runs the search on TPU (BASELINE.json —
"`jepsen.checker/linearizable` gains an `:algorithm :jax` option behind the
existing Checker protocol").

Algorithms:
  * ``"jax"``  — pack to event tensors, run the on-device frontier kernel
                 (ops/linear_scan.py); batched across histories.
  * ``"pallas"`` — like "jax" but dense-domain batches run the Pallas
                 kernel (ops/pallas_scan.py, frontier pinned in VMEM;
                 interpret mode off-TPU). Proven on TPU v5e hardware
                 2026-07-30; the vmapped XLA dense kernel measured ~2.3×
                 faster on the north-star batch (it parallelizes the tiny
                 per-history frontiers across the batch, the Pallas grid
                 is sequential), so "auto" keeps dense — this selector is
                 the explicit choice and the ablation hook.
  * ``"cpu"``  — the unbounded host frontier search (wgl_cpu.py).
  * ``"dfs"``  — the knossos/porcupine-style DFS-with-undo (dfs_cpu.py):
                 a genuinely different search order.
  * ``"race"`` — run the on-device frontier kernel AND the DFS engine
                 concurrently; per history, the first engine to decide
                 wins — the knossos.competition/analysis analogue
                 (reference raft_test.clj:26,41,64: :linear vs :wgl,
                 first finisher's answer is taken).
  * ``"auto"`` — jax when the history fits the kernel window, with sound
                 escalation: any verdict the kernel cannot certify
                 (window overflow, frontier overflow on an invalid result)
                 is re-checked on the CPU twin.

Soundness contract: a kernel "valid" is always sound (only reachable
configurations are ever retained, so a surviving linearization is real); a
kernel "invalid" is sound unless the frontier overflowed its fixed capacity,
in which case we escalate instead of reporting.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Optional, Sequence

import numpy as np

from ..history.ops import History
from ..history.packing import (EncodedHistory, bucket_rows, encode_history,
                               macro_events_on, pack_batch,
                               pack_macro_batch, pad_batch_bucketed)
from ..ops.dense_scan import (MASK_DENSE_MAX_SLOTS, MERGE_MAX_EVENTS,
                              dense_plans_grouped, make_dense_batch_checker)
from ..ops.linear_scan import (DEFAULT_N_CONFIGS, MAX_SLOTS, bucket_slots,
                               make_batch_checker, make_sort_chunk_checker)
from ..ops.segment_scan import LONG_HISTORY_MIN_EVENTS, check_segmented_batch
from ..platform import degraded_note, env_int
from . import autotune
from .base import Checker, INVALID, UNKNOWN, VALID
from .dfs_cpu import SearchBudgetExceeded, check_encoded_dfs
from .schedule import (ChunkLaunch, build_dense_launches, note_tier,
                       run_chunked, scan_chunk)
from .wgl_cpu import FrontierOverflow, check_encoded_cpu


#: Default CPU-frontier cap. The search is worst-case exponential in the
#: concurrency window (SURVEY.md §7.4.1); an unbounded fallback would hang
#: rather than answer on adversarial histories (e.g. 40 mutually-concurrent
#: writes). Capped, it reports "unknown" instead — the same stance the
#: reference community takes when knossos becomes "unfeasible to verify"
#: (reference doc/intro.md:35-41), but as a clean verdict, not an OOM.
DEFAULT_MAX_CPU_CONFIGS = 1 << 18

#: Per-shape platform routing (VERDICT r3 #4): with the chip behind a
#: network tunnel, tiny dense batches are dominated by launch+transfer
#: round trips, and the idle 8-way host mesh wins — measured on the
#: config-3 shape (≈600 sub-histories of ≤33 events: CPU 2516 vs TPU
#: 1391 hist/s, 2026-07-30 v5e) — while big batches amortize the trip
#: (north star 1000×~1750 events: TPU 488 vs CPU 25.6). The gate is the
#: group's scanned-cell count B×E; the default sits between the measured
#: winners' shapes (config-3 ≈19k cells → host, config-4 ≈250k → TPU)
#: and is env-tunable for re-ablation on other chip generations
#: (doc/running.md "Re-tuning the measured gates").
#: Parsed defensively (platform.env_int): a malformed
#: JGRAFT_ROUTE_MIN_CELLS used to crash every importer of this module at
#: import time; now it warns and falls back to the measured default.
PLATFORM_ROUTE_MIN_CELLS = env_int("JGRAFT_ROUTE_MIN_CELLS", 64_000,
                                   minimum=0)


# --------------------------------------- lin-rung fast path (ISSUE 14)
# The cheap-decision tier (checker/consistency.certify_encoded) runs as
# a PRE-KERNEL pass on the un-relaxed stream at the linearizable rung:
# a witness respecting every [OPEN, FORCE] interval of the original
# encoding IS a linearization, so a certified row is a sound VALID
# decided on the host in O(E·W) — no kernel launch, no batch slot.
# Undecided rows fall through to the ordinary ladder unchanged, so
# verdicts are bitwise-identical with the path force-disabled
# (JGRAFT_LIN_FASTPATH=0, the ablation/A-B arm). The worst case (host
# scan AND kernel) is bounded two ways: a length-scaled abort budget
# per row, and measured per-bucket gating (checker/autotune.py
# lin_fastpath_route) that routes low-hit buckets kernel-first.

#: Algorithms the fast path fronts: the kernel-launching selectors. An
#: explicit "cpu"/"dfs" keeps its host engine (tests use them as
#: oracles), and "race" already runs its own host engine concurrently.
#: Shared with graftd's dispatch fast lane (service/scheduler.py) so
#: the two surfaces can never drift.
LIN_FASTPATH_ALGOS = ("auto", "jax", "pallas")


def lin_fastpath_on() -> bool:
    """Whether the linearizable-rung pre-kernel certify pass runs.
    Default ON; ``JGRAFT_LIN_FASTPATH=0`` force-disables (defensive
    parse — garbage keeps the default)."""
    return env_int("JGRAFT_LIN_FASTPATH", 1, minimum=0) != 0


def lin_abort_steps() -> int:
    """Per-event abort budget for the fast path's host scan
    (``JGRAFT_LIN_FASTPATH_ABORT``, default 32 `model.step` calls per
    stream event; 0 = unbounded). A hopeless row aborts after
    budget·E steps, bounding its cost to a fraction of its kernel
    wall. Calibrated on the 200×1k host-CPU A/B (2026-08-04): valid
    rows certify in ~2–8 step calls per event (register 8 ms/row, the
    heaviest backtracking family queue ~15 ms/row, both far under the
    budget), while an uncertifiable row at 128/event burned ~2.5× its
    per-row kernel wall — 32/event keeps the worst case under it."""
    return env_int("JGRAFT_LIN_FASTPATH_ABORT", 32, minimum=0)


_FP_LOCK = threading.Lock()
_FP_ZERO = {"rows_scanned": 0, "rows_certified": 0, "rows_gated": 0,
            "rows_rung_skipped": 0, "events_scanned": 0,
            "certify_wall_s": 0.0}
_FP_COUNTERS = dict(_FP_ZERO)


def _fp_bump(**kw) -> None:
    with _FP_LOCK:
        for k, v in kw.items():
            _FP_COUNTERS[k] += v


def fastpath_counters() -> dict:
    """Process-wide lin-fastpath counters (non-destructive):
    rows_scanned/rows_certified (hit-rate numerator/denominator),
    rows_gated (routed kernel-first by the measured gate),
    rows_rung_skipped (weak-rung re-entries that skipped the redundant
    second scan — the ISSUE-14 double-scan satellite's evidence), and
    the summed certify wall."""
    with _FP_LOCK:
        return dict(_FP_COUNTERS)


def consume_fastpath_counters() -> dict:
    """Return and reset the counters (bench.py reads one window's
    worth)."""
    global _FP_COUNTERS
    with _FP_LOCK:
        out = dict(_FP_COUNTERS)
        _FP_COUNTERS = dict(_FP_ZERO)
        return out


def lin_fastpath_pass(encs: Sequence[EncodedHistory], model,
                      note: bool = True) -> list:
    """Run the certifier over a linearizable-rung batch; returns one
    result dict per row, None where undecided (the caller sends those
    through the kernel ladder). Rows are grouped into the autotuner's
    gating buckets; gated buckets are skipped wholesale (counted), and
    every scanned bucket's (rows, hits, wall) feeds the gate's record.
    Also the graftd fast lane's engine (service/scheduler.py), which
    passes ``note=False``: its all-or-nothing rule may DISCARD a
    partially-certified request's results, and a discarded row must
    not be tier-attributed here only to be attributed again by the
    kernel that actually decides it — the lane notes tiers itself for
    the requests it delivers. The `fastpath_counters` bumps stay
    unconditional: rows_scanned/rows_certified count SCAN outcomes
    (the gate's hit-rate evidence), not delivered verdicts."""
    from .certify_batch import certify_many

    results: list = [None] * len(encs)
    fam = type(model).__name__
    buckets: dict = {}
    for i, e in enumerate(encs):
        if e.n_events <= 0:
            continue  # trivial rows keep their "trivial" tier
        buckets.setdefault(
            autotune.lin_fastpath_sig(fam, e.n_events), []).append(i)
    abort = lin_abort_steps()
    for sig, idxs in buckets.items():
        if not autotune.lin_fastpath_route(sig):
            _fp_bump(rows_gated=len(idxs))
            continue
        t0 = time.perf_counter()
        hits = 0
        # whole bucket through the batched certifier core (ISSUE 15;
        # outcome-identical to the per-row scalar loop — the
        # JGRAFT_CERTIFY_BATCH=0 arm — with the same per-row length-
        # scaled abort budgets)
        certs = certify_many(
            [encs[i] for i in idxs], model,
            max_steps=[abort * max(encs[i].n_events, 1) if abort
                       else None for i in idxs])
        for i, (ok, tier, _) in zip(idxs, certs):
            if ok:
                hits += 1
                results[i] = {
                    "valid?": VALID,
                    "algorithm": "greedy-witness",
                    "op-count": encs[i].n_ops,
                    "concurrency-window": encs[i].n_slots,
                    # namespaced distinctly from the weak-rung
                    # certifier's greedy/backtrack so fleet tier
                    # attribution never conflates the two hit-rates
                    "decided-tier": tier + "@lin",
                }
        dt = time.perf_counter() - t0
        # Fair-share wall attribution, same stance as the weak rung:
        # every scanned row (certified or not) cost ~dt/len(idxs); the
        # certified rows book that share, the undecided rows' verdict
        # cost is the kernel tier's wall.
        per_row = dt / max(len(idxs), 1)
        if note:
            for i in idxs:
                if results[i] is not None:
                    note_tier(results[i]["decided-tier"],
                              wall_s=per_row)
        autotune.lin_fastpath_observe(sig, rows=len(idxs), hits=hits,
                                      wall_s=dt)
        _fp_bump(rows_scanned=len(idxs), rows_certified=hits,
                 events_scanned=sum(encs[i].n_events for i in idxs),
                 certify_wall_s=dt)
    return results


def _route_group_to_host(n_rows: int, n_events: int) -> bool:
    """True when a dense window group should run on the host CPU backend
    even though the default backend is a TPU. JGRAFT_PLATFORM_ROUTE
    forces the answer (tpu|cpu); auto applies the measured cell gate."""
    mode = os.environ.get("JGRAFT_PLATFORM_ROUTE", "auto")
    if mode == "tpu":
        return False
    if mode == "cpu":
        return True
    if n_events > MERGE_MAX_EVENTS:
        # LONG groups are depth-bound, not launch-bound: the B·E cell
        # gate (calibrated on config-3's tiny uniform-window batches)
        # undercounts their kernel work by the 2^W·S factor, and a
        # small merged cluster (e.g. 2×20k events = 40k cells) would
        # otherwise land on the throughput-bound host — the exact
        # placement the merged-launch policy measured 2.2× slower.
        return False
    import jax

    if jax.default_backend() != "tpu":
        return False  # already on the host — nothing to route
    try:
        jax.local_devices(backend="cpu")
    except RuntimeError:
        return False  # cpu backend unavailable (JAX_PLATFORMS pinned)
    return n_rows * n_events < PLATFORM_ROUTE_MIN_CELLS


def check_histories(
    histories: Sequence[History],
    model,
    algorithm: str = "auto",
    n_configs: Optional[int] = None,
    n_slots: Optional[int] = None,
    witness: bool = False,
    max_cpu_configs: Optional[int] = DEFAULT_MAX_CPU_CONFIGS,
    consistency: str = "linearizable",
) -> list[dict]:
    """Check a batch of histories; returns one result dict per history.

    The batch is the unit of TPU work: all histories are packed, padded to a
    common event length, and verified in one vmapped kernel launch.
    n_configs/n_slots default to auto: the concurrency window is sized to
    the batch's real maximum (exact ≤16 slots, else bucketed to
    SLOT_BUCKETS 31/63/95/127) — per-event closure work scales with C×W,
    so a snug window is a direct kernel-speed win.

    ``consistency`` selects the verdict's rung on the weaker-consistency
    ladder (checker/consistency.py): "linearizable" (default),
    "sequential", "session" — weaker rungs re-run the SAME machinery on
    a relaxed-precedence re-encoding, with a greedy witness fast path.
    """
    encs = [encode_history(h, model) for h in histories]
    return check_encoded(encs, model, algorithm, n_configs, n_slots,
                         witness, max_cpu_configs,
                         consistency=consistency)


def check_encoded(
    encs: Sequence[EncodedHistory],
    model,
    algorithm: str = "auto",
    n_configs: Optional[int] = None,
    n_slots: Optional[int] = None,
    witness: bool = False,
    max_cpu_configs: Optional[int] = DEFAULT_MAX_CPU_CONFIGS,
    distribute: bool = True,
    consistency: str = "linearizable",
    lin_fastpath: Optional[bool] = None,
) -> list[dict]:
    """Pack-once/check-many entry: verify histories that are ALREADY
    encoded (`history.packing.encode_history`), one result dict each.

    This is the seam the checking service (service/scheduler.py) batches
    through: graftd encodes every submission exactly once at admission
    (the encoding bytes are also its result-cache fingerprint), then
    re-enters here with the concatenation of many tenants' encodings —
    the dense grouping, pow2+midpoint bucketing, and chunked wavefront
    below treat those foreign rows exactly like a single caller's batch
    (rows are independent along the batch axis; doc/checker-design.md
    §8). `check_histories` is the encode-then-delegate wrapper.

    Multi-host (ISSUE 7): inside an initialized `jax.distributed`
    cluster this entry runs the SHARDED wavefront — each process checks
    only its contiguous row shard through the ordinary machinery below
    and the per-row verdicts are exchanged so every process returns the
    full batch (parallel/distributed.run_sharded; placement model in
    doc/checker-design.md §10). The caller contract is SPMD: every
    process calls with the same batch — true of the bench and the
    `check` CLI run once per host. `distribute=False` (graftd's
    per-host scheduler, whose admission queues are host-local) and
    ``JGRAFT_DISTRIBUTED=0`` both pin the single-process path; outside
    a cluster the seam is inert by construction.

    ``consistency`` (checker/consistency.py): a weaker rung relaxes the
    batch's FORCE placement ONCE here, greedy-certifies what a host
    witness scan can, and re-enters this entry at the linearizable rung
    with the relaxed encodings — so every downstream path (dense
    grouping, bucketing, chunked wavefront, distribution, graftd
    coalescing) serves the rungs unchanged. Results carry a
    ``consistency`` key whenever a non-default rung decided them.

    ``lin_fastpath`` (ISSUE 14): None = the default — at the
    linearizable rung with a kernel algorithm, run the pre-kernel
    certify pass (`lin_fastpath_pass`) and send only undecided rows to
    the kernels. False = skip it; the weak-rung recursion below passes
    False because its rows were ALREADY scanned by the rung certifier
    on a superset-legality stream (a second scan of the relaxed bytes
    is pure waste — rows_rung_skipped counts the saved work), and
    graftd's fast lane passes False after certifying at dispatch.
    """
    from ..parallel import distributed

    consistency = _normalize_rung(consistency)
    if consistency != "linearizable":
        from .consistency import apply_rung

        t0 = time.perf_counter()
        relaxed, certified, tiers = apply_rung(encs, model, consistency)
        dt_cert = time.perf_counter() - t0
        results: list[Optional[dict]] = [None] * len(encs)
        todo: list[int] = []
        # Wall attribution: each certified row carries its FAIR share
        # of the whole certify+relax pass (dt_cert / batch rows) — the
        # undecided rows' share stays unattributed here on purpose
        # (their verdict cost is the kernel tier's wall); booking the
        # full batch wall onto the few certified rows would inflate
        # the cheap tier's reported cost arbitrarily.
        per_row = dt_cert / max(len(encs), 1)
        for i, (enc, ok) in enumerate(zip(relaxed, certified)):
            if ok:
                results[i] = {
                    "valid?": VALID, "algorithm": "greedy-witness",
                    "op-count": enc.n_ops,
                    "concurrency-window": enc.n_slots,
                    "decided-tier": tiers[i],
                }
                note_tier(tiers[i], wall_s=per_row)
            else:
                todo.append(i)
        # Exact cycle tier (ISSUE 13, checker/cycle.py): a dependency
        # cycle among the required ops is a sharp SC refutation, and
        # at the sequential rung it implies the kernel verdict INVALID
        # (doc §15) — so undecided rows consult it BEFORE paying the
        # relax + kernel-ladder pass. The tier only ever refutes;
        # cycle-free rows fall through unchanged.
        cycle_skips: dict = {}
        if todo and consistency == "sequential":
            from .cycle import cycle_tier_on, find_cycles

            if cycle_tier_on():
                t0 = time.perf_counter()
                cyc = find_cycles([encs[i] for i in todo], model)
                dt_cyc = time.perf_counter() - t0
                hits = [(j, i) for j, i in enumerate(todo)
                        if cyc[j] is not None and "cycle" in cyc[j]]
                # rows too big for the tier get the size-skip stamped
                # on whatever result the ladder reaches below (ISSUE
                # 19 satellite: the cap skip used to be invisible)
                cycle_skips.update(
                    (i, cyc[j]["skipped-size"]) for j, i in
                    enumerate(todo)
                    if cyc[j] is not None and "skipped-size" in cyc[j])
                for j, i in hits:
                    results[i] = {
                        "valid?": INVALID, "algorithm": "cycle",
                        "op-count": encs[i].n_ops,
                        "concurrency-window": encs[i].n_slots,
                        "decided-tier": "cycle",
                        "cycle": cyc[j]["cycle"],
                        "exact-sc-refutation": True,
                    }
                    note_tier("cycle", wall_s=dt_cyc / len(hits))
                if hits:
                    todo = [i for i in todo if results[i] is None]
        if todo:
            # lin_fastpath=False (ISSUE-14 satellite): these rows were
            # already scanned by the rung certifier above — on the
            # ORIGINAL stream and again on the relaxed one, whose
            # legality is a superset — so the lin fast path re-scanning
            # the relaxed bytes could never certify what apply_rung
            # just failed to. The counter proves the skip fires — and
            # only counts when the fast path would otherwise have run
            # (a JGRAFT_LIN_FASTPATH=0 ablation run must keep its
            # lin-fastpath counters absent, not claim saved scans).
            if algorithm in LIN_FASTPATH_ALGOS and lin_fastpath_on():
                _fp_bump(rows_rung_skipped=len(todo))
            sub = check_encoded([relaxed[i] for i in todo], model,
                                algorithm, n_configs, n_slots, witness,
                                max_cpu_configs, distribute,
                                consistency="linearizable",
                                lin_fastpath=False)
            for i, r in zip(todo, sub):
                results[i] = r
        if consistency == "session":
            _annotate_sc_refutations(encs, results, model)
        for i, n_skipped in cycle_skips.items():
            if results[i] is not None:
                results[i]["cycle-skipped-size"] = n_skipped
        for r in results:
            r["consistency"] = consistency
        return results  # type: ignore[return-value]

    def _kernel_path(rest):
        if distribute and distributed.wavefront_active() and len(rest) > 1:
            return distributed.run_sharded(
                rest,
                lambda sub: _check_encoded(sub, model, algorithm,
                                           n_configs, n_slots, witness,
                                           max_cpu_configs),
                # the result-detail exchange (ISSUE 11 tentpole (d))
                # keys its store records over (model, algorithm, row
                # encoding); inert unless a shared store dir is
                # configured
                model=model, algorithm=algorithm)
        return _check_encoded(rest, model, algorithm, n_configs,
                              n_slots, witness, max_cpu_configs)

    # Lin-rung pre-kernel fast path (ISSUE 14): certify on the host,
    # evict VALID rows from the batch BEFORE grouping/bucketing/
    # chunked-wavefront. Inside an active distributed wavefront the
    # certify pass itself is deterministic, but the measured gate
    # (autotune linfp records) is HOST-LOCAL state — two cluster
    # processes with different gate histories would evict different
    # rows and the SPMD collectives would mismatch — so sharded
    # batches stay kernel-first UNLESS the shared gate store
    # (ISSUE 18: autotune.linfp_shared_dir, JGRAFT_LINFP_DIR /
    # cluster-dir fallback) is configured: then every rank seeds its
    # gate from the same published snapshot and routes identically.
    # Residual race, documented: a publish landing between two ranks'
    # FIRST touch of the same bucket can still diverge their routing —
    # worst case a collective mismatch (an error/hang, i.e. liveness),
    # never a verdict change. graftd's per-host lane is unaffected
    # (its scheduler pins distribute=False).
    distributing = (distribute and distributed.wavefront_active()
                    and len(encs) > 1)
    gate_shared = autotune.linfp_shared_dir() is not None
    fp = None
    if (lin_fastpath is not False and encs
            and (not distributing or gate_shared)
            and algorithm in LIN_FASTPATH_ALGOS and lin_fastpath_on()):
        fp = lin_fastpath_pass(encs, model)
        if not any(r is not None for r in fp):
            fp = None
    if fp is not None:
        todo = [i for i, r in enumerate(fp) if r is None]
        results = fp
        if todo:
            for i, r in zip(todo, _kernel_path([encs[i] for i in todo])):
                results[i] = r
    else:
        results = _kernel_path(encs)
    note = degraded_note()
    if note:
        # The platform silently degraded (TPU probe failed / tunnel
        # dropped mid-flight): stamp every result so a degraded run is
        # distinguishable from an intended-CPU run in stored artifacts
        # (the bench's platform_note, now in the checker metadata too).
        for r in results:
            r.setdefault("platform-degraded", note)
    return results


def _normalize_rung(name) -> str:
    """Cheap-path normalization: the default rung never imports the
    consistency module (keeps the hot linearizable path import-free)."""
    if name in (None, "linearizable"):
        return "linearizable"
    from .consistency import normalize_consistency

    return normalize_consistency(name)


def _annotate_sc_refutations(encs, results, model) -> None:
    """Session-rung SC evidence (ISSUE 13): the implemented session
    guarantee (monotonic reads + read-your-writes) does NOT imply full
    sequential consistency — a monotonic-writes violation can honestly
    PASS the rung — so a dependency cycle here is attached as an
    annotation, never a verdict change: ``sc-refuted`` marks results
    whose history is exactly proven non-SC even though the weaker rung
    holds (the sharper-than-relaxation acceptance evidence, pinned in
    tests/test_cycle.py). Best-effort and ablation-gated like the
    verdict tier."""
    from .cycle import cycle_tier_on, find_cycles

    if not cycle_tier_on():
        return
    try:
        cyc = find_cycles(encs, model)
    except Exception:
        return  # evidence must never take down a sound verdict
    for r, c in zip(results, cyc):
        if c is None or r is None:
            continue
        if "cycle" in c:
            r["sc-refuted"] = True
            r["sc-cycle"] = c["cycle"]
        elif "skipped-size" in c:
            r["cycle-skipped-size"] = c["skipped-size"]


def _check_encoded(
    encs: Sequence[EncodedHistory],
    model,
    algorithm: str = "auto",
    n_configs: Optional[int] = None,
    n_slots: Optional[int] = None,
    witness: bool = False,
    max_cpu_configs: Optional[int] = DEFAULT_MAX_CPU_CONFIGS,
) -> list[dict]:
    results: list[Optional[dict]] = [None] * len(encs)

    if algorithm == "dfs":
        return [_check_dfs(e, model, witness,
                           max_steps=DEFAULT_DFS_BUDGET) for e in encs]

    if algorithm == "race":
        return _race(encs, model, n_configs, n_slots, witness,
                     max_cpu_configs)

    if algorithm == "auto":
        # Wide-window fast path: a history whose concurrency window is
        # beyond every dense kernel is frontier-hostile (breadth-first
        # cost ~2^W), but usually DFS-trivial when valid (one witness
        # suffices; a round-3 hell-soak 19-slot counter history decided
        # in 4.3k DFS configs after 70s of doomed frontier work). Spend
        # a small DFS budget first; undecided histories take the normal
        # kernel → CPU → full-budget-DFS ladder below.
        for i, e in enumerate(encs):
            if results[i] is None and e.n_slots > MASK_DENSE_MAX_SLOTS \
                    and e.n_events > 0:
                r = _check_dfs(e, model, witness,
                               max_steps=FAST_DFS_BUDGET)
                if r["valid?"] is not UNKNOWN:
                    results[i] = r

    if algorithm in ("jax", "auto", "pallas"):
        undecided = [e if results[i] is None else None
                     for i, e in enumerate(encs)]
        todo = [e for e in undecided if e is not None]
        want_pallas = "pallas" if algorithm == "pallas" else None
        try:
            jax_res = _jax_pass(todo, model, n_configs, n_slots,
                                kernel=want_pallas)
        except Exception as e:
            # An env-pinned backend that cannot initialize (JAX_PLATFORMS
            # names a TPU plugin whose registration was skipped) or whose
            # tunnel drops mid-flight must degrade to the host, not
            # surface as an unknown-verdict checker crash — the bench
            # learned this in round 2; round 4's /verify drive caught the
            # library path. Same predicate as the bench's re-exec.
            from ..platform import (is_backend_init_failure, note_degraded,
                                    pin_cpu, reset_backends)

            if not is_backend_init_failure(e):
                raise
            note_degraded(f"degraded to host CPU mid-check: "
                          f"{type(e).__name__}: {e}"[:300])
            pin_cpu()
            # A backend that initialized and THEN dropped is cached;
            # without this the retry re-hits the dead backend (ADVICE r4).
            reset_backends()
            jax_res = _jax_pass(todo, model, n_configs, n_slots,
                                kernel=want_pallas)
        it = iter(jax_res)
        results = [r if r is not None else next(it) for r in results]
        if algorithm in ("jax", "pallas"):
            for i, r in enumerate(results):
                if r is None:
                    results[i] = {
                        "valid?": UNKNOWN,
                        "algorithm": "jax",
                        "error": "kernel capacity exceeded "
                        f"(window {encs[i].n_slots} slots); "
                        "use algorithm='auto' or 'cpu'",
                    }
            return results  # type: ignore[return-value]

    for i, r in enumerate(results):
        dfs_exhausted = False
        if r is None and algorithm == "auto" and \
                encs[i].n_slots > MASK_DENSE_MAX_SLOTS:
            # Wide windows that the kernels couldn't decide: try the
            # budgeted DFS BEFORE the CPU frontier twin — it explores in
            # a different order and often finds a single witness where
            # breadth-first frontiers explode (a round-3 hell-soak
            # counter history with a 19-slot crashed window decided in
            # 4.3k DFS configs after the 2^18-config frontier overflowed;
            # frontier-first wasted minutes on it). Budget exhaustion
            # falls through to the frontier, whose overflow cap is the
            # final "unfeasible to verify" verdict (reference
            # doc/intro.md:35-41 stance).
            r2 = _check_dfs(encs[i], model, witness,
                            max_steps=DEFAULT_DFS_BUDGET)
            if r2["valid?"] is not UNKNOWN:
                results[i] = r2
                continue
            dfs_exhausted = True  # deterministic: a re-run cannot differ
        if results[i] is None:
            results[i] = _check_cpu(encs[i], model, witness, max_cpu_configs)
        if results[i].get("valid?") is UNKNOWN and algorithm == "auto" \
                and not dfs_exhausted:
            r2 = _check_dfs(encs[i], model, witness,
                            max_steps=DEFAULT_DFS_BUDGET)
            if r2["valid?"] is not UNKNOWN:
                results[i] = r2
    return results  # type: ignore[return-value]


def _jax_pass(encs, model, n_configs=None, n_slots=None, kernel=None,
              note: bool = True):
    """Run the on-device pass over a batch of encoded histories. Returns a
    result dict per history, or None where the kernel could not certify a
    verdict (window beyond MAX_SLOTS, or frontier overflow at top
    capacity) — the caller escalates those. `kernel="pallas"` (or the
    JGRAFT_KERNEL=pallas env override) routes dense-domain groups through
    the Pallas kernel instead of the XLA dense kernel."""
    results: list[Optional[dict]] = [None] * len(encs)
    cap = n_slots or MAX_SLOTS
    fits = [i for i, e in enumerate(encs)
            if e.n_slots <= cap and e.n_events > 0]
    for i, e in enumerate(encs):
        if e.n_events == 0:
            if note:
                note_tier("trivial")
            results[i] = {"valid?": VALID, "algorithm": "trivial",
                          "op-count": 0, "decided-tier": "trivial"}
    # Resolved before any routing: the group loop below rebinds `kernel`
    # to the compiled callable, and the segment router must also honor
    # an explicit pallas request (an ablation asking for pallas must not
    # silently measure the segmented XLA kernel).
    want_pallas = (kernel == "pallas" or
                   os.environ.get("JGRAFT_KERNEL") == "pallas")
    # Macro-event compaction (ISSUE-4 tentpole): every kernel family
    # consumes the macro stream unless JGRAFT_MACRO_EVENTS=0 pins the
    # legacy one-event-per-step stream (the differential/ablation path;
    # verdicts are bitwise-identical either way). Eligibility/grouping
    # above stays on the legacy encoding — only kernel consumption
    # switches. The segmented long-history path keeps legacy events
    # (its cut/basis planner reasons about per-event quiescence).
    _group_pack = pack_macro_batch if macro_events_on() else pack_batch
    if fits and n_configs is None and n_slots is None and not want_pallas:
        # Long histories first: the segmented scan (ops/segment_scan.py)
        # cuts a 100k+-event stream at quiescent boundaries and runs the
        # segments concurrently — the blockwise treatment of SURVEY §5.7.
        # Exact (differentially pinned vs the monolithic kernels);
        # ineligible histories (short, cut-free, non-dense) fall through.
        #
        # Routed only where measured to win (TPU v5e, 2026-07-30): a
        # FEW very long histories — depth is the wall-clock driver and
        # segmentation trades it for basis-redundant width the VPU
        # absorbs (config #5: 4.0s vs 4.4s monolithic). With many long
        # histories the monolithic vmap already fills the chip and the
        # basis redundancy only hurts (16×10k: 12.5 vs 2.0 hist/s);
        # on CPU the redundant width swamps the host outright (>10×
        # slower). JGRAFT_SEGMENT=1/0 forces the choice (tests, ablation).
        long_idx = [i for i in fits
                    if encs[i].n_events >= LONG_HISTORY_MIN_EVENTS]
        if long_idx and not _segment_routing_on(len(long_idx)):
            long_idx = []
        if long_idx:
            t0 = time.perf_counter()
            seg = check_segmented_batch([encs[i] for i in long_idx], model)
            dt = time.perf_counter() - t0
            n_done = sum(1 for r in seg if r is not None)
            for j, i in enumerate(long_idx):
                if seg[j] is not None:
                    r = _jx(VALID if seg[j]["valid"] else INVALID, encs[i],
                            dt / max(n_done, 1), kernel="dense-seg",
                            note=note)
                    r["segments"] = seg[j]["segments"]
                    results[i] = r
            fits = [i for i in fits if results[i] is None]
    if fits:
        # Dense-bitset kernel next: exact (no overflow, no escalation)
        # and ~10× the sort kernel when the model's state domain is
        # enumerable and the window is small — the shapes the reference's
        # own workloads produce. Pinned n_configs/n_slots are sort-kernel
        # knobs, so an explicit pin keeps the sort path (tests rely on
        # capacity semantics).
        grouped, rest = (dense_plans_grouped(model,
                                             [encs[i] for i in fits])
                         if n_configs is None and n_slots is None
                         else ([], list(range(len(fits)))))
        if grouped and scan_chunk() > 0 and not want_pallas:
            # Chunked wavefront (ISSUE 3, checker/schedule.py): the
            # event scan runs in fixed-size chunks, decided/exhausted
            # rows are evicted and survivors recompacted between
            # chunks, groups early-exit when empty, and every group's
            # chunk is row-sharded over the device mesh and dispatched
            # (async) before any result is blocked on — the placement
            # policy lives in build_dense_launches. The schedule
            # covers the event length the MONOLITHIC kernel would scan
            # (pad_batch_bucketed's floor_e=32 series for short
            # groups, exact for LONG ones) so `early_exit` reports
            # genuinely skipped reference work; host-routed groups
            # (PLATFORM_ROUTE_MIN_CELLS) carry their chunks on the
            # host device. JGRAFT_SCAN_CHUNK=0 restores the monolithic
            # reference launch loop below; the Pallas ablation keeps
            # the monolithic path (its grid kernel owns its own event
            # loop).
            triples = []
            for idxs, plan in grouped:
                sub = [fits[j] for j in idxs]
                sub_encs = [encs[i] for i in sub]
                # Per-bucket autotuned plan (checker/autotune.py):
                # consulted per window group — a persisted plan loads,
                # a big-enough unplanned bucket measures once in
                # process, everything else (JGRAFT_AUTOTUNE=0, small
                # groups, LONG clusters) keeps today's defaults. The
                # plan's macro payload cap acts here at pack time; its
                # chunk/fan-out halves act in build_dense_launches.
                tuned = autotune.tuned_group_plan(model, plan, sub_encs)
                batch = (autotune.pack_group(sub_encs, tuned)
                         if tuned is not None
                         else _group_pack(sub_encs))
                triples.append((sub, plan, batch, tuned))
            launches, subs = build_dense_launches(
                model, triples, host_route=_route_group_to_host)
            with _maybe_profile():
                outs = run_chunked(launches)
            for sub, out in zip(subs, outs):
                # Slices overlap on devices, so per-launch kernel
                # walls are not additive; each row reports its slice's
                # (overlapped) wall share, same stance as the
                # monolithic marginal-delta attribution.
                dt = out.wall_s / max(len(sub), 1)
                for j, i in enumerate(sub):
                    r = _jx(VALID if out.ok[j] else INVALID, encs[i],
                            dt, kernel=out.tag, note=note)
                    r["chunked"] = True
                    results[i] = r
        elif grouped:
            # Launch every window group BEFORE blocking on any result:
            # jax dispatch is async, so the device pipelines the groups
            # while the host packs the next one — and when the chip sits
            # behind a network tunnel (this build's deployment), blocking
            # per group would serialize a full round trip per window
            # group (VERDICT r3 #3: the config-4 end-to-end gap was
            # launch-loop overhead, not kernel time).
            t0 = time.perf_counter()
            launched = []  # (sub, tag, ok_device, B)
            n_launched = 0
            with _maybe_profile():
                for idxs, plan in grouped:
                    sub = [fits[j] for j in idxs]
                    batch = _group_pack([encs[i] for i in sub])
                    # Bucketing trades padding work for jit-cache
                    # stability. For LONG histories the trade inverts:
                    # bucketing a 12k-event cluster to 16k events adds
                    # 33% sequential scan depth to every member, while
                    # the compile cache only ever sees a handful of
                    # long launches per process (merged clusters —
                    # _merge_long_groups — make them fewer still, and
                    # can exceed 16 rows, so exactness keys on
                    # long-ness alone, not group size).
                    e_len = batch["events"].shape[1]
                    # Exactness (and the host gate below) key on the
                    # LEGACY event length: the policies were calibrated
                    # on it, and a macro batch's ~2× shorter row count
                    # must not silently halve their thresholds.
                    e_legacy = batch.get("legacy_events", e_len)
                    exact = e_legacy > MERGE_MAX_EVENTS
                    ev, (val_of,), B = pad_batch_bucketed(
                        batch["events"], (plan.val_of,),
                        floor_b=len(sub) if exact else 8,
                        floor_e=None if exact else 32)
                    tag = plan.kernel_tag
                    if want_pallas and plan.kind == "domain":
                        # Pallas path (ops/pallas_scan.py): same search,
                        # frontier pinned in VMEM. Interpret off-TPU.
                        import jax

                        from ..ops.pallas_scan import (
                            make_pallas_batch_checker)
                        kernel = make_pallas_batch_checker(
                            model, plan.n_slots, plan.n_states,
                            ev.shape[1],
                            interpret=jax.default_backend() != "tpu",
                            macro_p=batch.get("macro_p"))
                        tag = "pallas"
                    else:
                        kernel = make_dense_batch_checker(
                            model, plan.kind, plan.n_slots, plan.n_states,
                            macro_p=batch.get("macro_p"))
                        if _route_group_to_host(
                                ev.shape[0],
                                e_legacy if exact
                                else bucket_rows(e_legacy, 32)):
                            # Tiny batch + tunneled chip: the host mesh
                            # wins (see PLATFORM_ROUTE_MIN_CELLS).
                            # Committed inputs carry the computation to
                            # the CPU backend; the jit cache keys on
                            # sharding, so both placements coexist.
                            import jax

                            host = jax.local_devices(backend="cpu")[0]
                            ev = jax.device_put(ev, host)
                            val_of = jax.device_put(val_of, host)
                            tag += "@host"
                    ok, _ = kernel(ev, val_of)
                    launched.append((sub, tag, ok, B))
                    n_launched += len(sub)
                t_prev = t0
                for g, (sub, tag, ok, B) in enumerate(launched):
                    # blocks: device → host
                    ok = np.asarray(ok)[:B]  # lint: allow(host-sync)
                    t_now = time.perf_counter()
                    # Per-history time under pipelining: the MARGINAL
                    # wall this group added (delta between successive
                    # blocking reads; the first group also absorbs the
                    # shared pack+launch span). Groups overlap on
                    # device, so exact per-group kernel attribution
                    # does not exist — this keeps sums meaningful.
                    dt = t_now - t_prev
                    t_prev = t_now
                    for j, i in enumerate(sub):
                        results[i] = _jx(VALID if ok[j] else INVALID,
                                         encs[i], dt / max(len(sub), 1),
                                         kernel=tag, note=note)
        # Histories beyond the dense caps continue to the sort ladder.
        fits = [fits[j] for j in rest]
    if fits:
        eff_slots = n_slots or bucket_slots(
            max(encs[i].n_slots for i in fits)
        )
        # Capacity ladder: per-event work is linear in the frontier
        # capacity C, and a "valid" at small C is final (overflow can
        # only drop configurations, i.e. cause false-INVALID, never
        # false-VALID) — so run everything at a small C and re-run only
        # the overflowed minority at full capacity. Typical histories
        # (bounded concurrency window) decide on the first rung, ~4×
        # cheaper than launching everything at DEFAULT_N_CONFIGS.
        ladder = ([n_configs] if n_configs else
                  [64, DEFAULT_N_CONFIGS] if DEFAULT_N_CONFIGS > 64
                  else [DEFAULT_N_CONFIGS])
        remaining = fits
        for rung, eff_configs in enumerate(ladder):
            # The C-ladder consumes the macro stream too: every rung's
            # kernel (chunked or monolithic) keys on the batch's P.
            # The rung consults the autotuner like the dense groups do
            # (family "sort", capacity in the signature).
            rung_encs = [encs[i] for i in remaining]
            tuned = (autotune.tuned_sort_plan(model, rung_encs,
                                              eff_configs, eff_slots)
                     if scan_chunk() > 0 else None)
            batch = (autotune.pack_group(rung_encs, tuned)
                     if tuned is not None else _group_pack(rung_encs))
            t0 = time.perf_counter()
            if scan_chunk() > 0:
                # Chunked sort scan (ISSUE 3): same rung, but decided
                # rows evict between chunks and the rung early-exits
                # when every row is decided. The ladder still blocks
                # per rung — the escalation decision needs the flags.
                # A tuned fan-out shards the rung over the mesh — the
                # pre-autotune rung was single-device, which the 8-vdev
                # host measured 1.84× slower at sort shapes (autotune
                # docstring); no plan keeps today's placement.
                rung_sharding = autotune.sort_rung_sharding(tuned)
                init_fn, step_fn = make_sort_chunk_checker(
                    model, eff_configs, eff_slots,
                    mesh=getattr(rung_sharding, "mesh", None),
                    macro_p=batch.get("macro_p"))
                e_sched = bucket_rows(batch["events"].shape[1], 32)
                with _maybe_profile():
                    [out] = run_chunked([ChunkLaunch(
                        events=batch["events"],
                        n_events=batch["n_events"],
                        init_fn=init_fn, step_fn=step_fn,
                        e_sched=e_sched, device=rung_sharding,
                        tag="sort",
                        chunk=(tuned.scan_chunk or max(e_sched, 1))
                        if tuned is not None else None)])
                ok, overflow = out.ok, out.overflow
            else:
                kernel = make_batch_checker(model, eff_configs, eff_slots,
                                            macro_p=batch.get("macro_p"))
                # Bucket both compile-shape dims (batch, events) to
                # powers of two so repeated calls hit the jit cache
                # instead of recompiling per batch size. Pad rows/events
                # are EV_PAD no-ops.
                ev, _, B = pad_batch_bucketed(batch["events"])
                with _maybe_profile():
                    ok, overflow = kernel(ev)
                ok, overflow = ok[:B], overflow[:B]
                # The ladder must block per rung to decide escalation.
                ok = np.asarray(ok)  # lint: allow(host-sync)
                overflow = np.asarray(overflow)  # lint: allow(host-sync)
            dt = time.perf_counter() - t0
            escalate = []
            for j, i in enumerate(remaining):
                if ok[j]:
                    results[i] = _jx(VALID, encs[i], dt / len(remaining),
                                     note=note)
                elif not overflow[j]:
                    results[i] = _jx(INVALID, encs[i],
                                     dt / len(remaining), note=note)
                elif rung + 1 < len(ladder):
                    escalate.append(i)
                # else: overflowed at top capacity → undecided (None)
            remaining = escalate
            if not remaining:
                break
    return results


def _segment_routing_on(n_long: int) -> bool:
    forced = os.environ.get("JGRAFT_SEGMENT")
    if forced is not None:
        return forced == "1"
    import jax

    return jax.default_backend() == "tpu" and n_long <= 2


#: DFS step budget in race mode: enough for any history the harness
#: produces at its scale, small enough that adversarial backtracking
#: cannot wedge the race (the frontier engines decide those).
DEFAULT_DFS_BUDGET = 4_000_000

#: Budget for auto mode's wide-window DFS fast path (sub-second):
#: valid histories typically decide in thousands of steps; adversarial
#: ones exhaust this quickly and fall through to the frontier ladder.
FAST_DFS_BUDGET = 300_000


def _race(encs, model, n_configs, n_slots, witness, max_cpu_configs):
    """Race the on-device frontier kernel against the DFS engine; per
    history the first decided verdict wins (knossos.competition analogue,
    reference raft_test.clj:26). Histories neither engine decides fall
    back to the capped CPU frontier — which can itself report UNKNOWN on
    adversarial histories (the reference community's stance when knossos
    becomes "unfeasible to verify", doc/intro.md:35-41)."""
    import threading

    decided: list[Optional[dict]] = [None] * len(encs)
    lock = threading.Lock()

    def record(i, res):
        with lock:
            if decided[i] is None:
                res["raced"] = True
                decided[i] = res
                # Tier attribution belongs to the WINNER only — the
                # losing engine's work on the same row must not double-
                # count rows (fractions would exceed 1.0).
                tier = res.get("decided-tier")
                if tier is not None:
                    note_tier(tier, wall_s=res.get("time-s", 0.0))

    def jax_side():
        try:
            rs = _jax_pass(encs, model, n_configs, n_slots, note=False)
        except Exception:
            # The DFS side carries the race — but never silently: an
            # always-failing kernel (model bug, shape regression) would
            # otherwise degrade every race to single-engine unnoticed.
            import logging
            logging.getLogger(__name__).warning(
                "race: jax engine failed, DFS/CPU carries this batch",
                exc_info=True)
            return
        for i, r in enumerate(rs):
            if r is not None:
                record(i, r)

    def dfs_side():
        # Cheapest histories first: win the race where DFS is strong.
        order = sorted(range(len(encs)), key=lambda i: encs[i].n_events)
        for i in order:
            with lock:
                if decided[i] is not None:
                    continue
            r = _check_dfs(encs[i], model, witness,
                           max_steps=DEFAULT_DFS_BUDGET, note=False)
            if r["valid?"] is not UNKNOWN:
                record(i, r)

    threads = [threading.Thread(target=jax_side),
               threading.Thread(target=dfs_side)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i, r in enumerate(decided):
        if r is None:
            decided[i] = _check_cpu(encs[i], model, witness, max_cpu_configs)
    return decided


def _check_dfs(enc: EncodedHistory, model, witness: bool = False,
               max_steps: Optional[int] = None, note: bool = True) -> dict:
    if enc.n_events == 0:
        if note:
            note_tier("trivial")
        return {"valid?": VALID, "algorithm": "trivial", "op-count": 0,
                "decided-tier": "trivial"}
    t0 = time.perf_counter()
    try:
        r = check_encoded_dfs(enc, model, max_steps=max_steps,
                              witness=witness)
    except SearchBudgetExceeded as e:
        return {"valid?": UNKNOWN, "algorithm": "dfs", "error": str(e)}
    if note:
        note_tier("host", wall_s=time.perf_counter() - t0)
    out = {
        "valid?": VALID if r.valid else INVALID,
        "algorithm": "dfs",
        "op-count": enc.n_ops,
        "concurrency-window": enc.n_slots,
        "configs-explored": r.configs_explored,
        "decided-tier": "host",
    }
    if not r.valid:
        out["failing-op-index"] = r.failing_op_index
    if r.witness is not None:
        out["witness"] = r.witness
    return out


def _maybe_profile():
    """XLA profiler hook (SURVEY.md §5.1): set JGRAFT_PROFILE_DIR to
    capture a TensorBoard-readable trace of the kernel launches."""
    profile_dir = os.environ.get("JGRAFT_PROFILE_DIR")
    if not profile_dir:
        return contextlib.nullcontext()
    import jax
    return jax.profiler.trace(profile_dir)



def kernel_tier(tag: str) -> str:
    """Decided-tier name of a kernel tag (ISSUE 13 attribution): the
    mask kernel is its own (cheapest) tier, every other dense-family
    kernel (domain / pallas / segmented) reports "dense", the sort
    ladder "sort"."""
    if "mask" in tag:
        return "mask"
    if "sort" in tag:
        return "sort"
    return "dense"


def _jx(valid, enc: EncodedHistory, secs: float,
        kernel: str = "sort", note: bool = True) -> dict:
    tier = kernel_tier(kernel)
    if note:
        note_tier(tier, wall_s=secs)
    return {
        "valid?": valid,
        "algorithm": "jax",
        "kernel": kernel,
        "op-count": enc.n_ops,
        "concurrency-window": enc.n_slots,
        "time-s": secs,
        "decided-tier": tier,
    }


def check_encoded_host(enc: EncodedHistory, model, witness: bool = False,
                       max_cpu_configs: Optional[int]
                       = DEFAULT_MAX_CPU_CONFIGS,
                       consistency: str = "linearizable",
                       lin_fastpath: Optional[bool] = None) -> dict:
    """Host-only verdict ladder for one encoded history: the capped CPU
    frontier first, the budgeted DFS when the frontier reports UNKNOWN —
    never a device launch. This is graftd's degrade path (the service
    re-checks a batch through it when the device pass raises mid-check),
    mirroring `auto` mode's escalation order without re-entering jax.
    A weaker ``consistency`` rung relaxes/greedy-certifies exactly like
    `check_encoded`, so degraded rung verdicts match the device path —
    and at the linearizable rung the same pre-frontier certify fast
    path runs (ISSUE 14; `lin_fastpath=False` skips it, e.g. graftd's
    fast lane having already certified at dispatch)."""
    if enc.n_events == 0:
        note_tier("trivial")
        return {"valid?": VALID, "algorithm": "trivial", "op-count": 0,
                "decided-tier": "trivial"}
    consistency = _normalize_rung(consistency)
    if consistency != "linearizable":
        from .consistency import apply_rung

        orig = enc

        def annotate_session(res: dict) -> dict:
            # Same sc-refuted evidence the device path attaches to
            # EVERY session-rung result (certified ones included) —
            # the degrade path must not silently drop it. Host DFS
            # arm: no device launch; best-effort like the device twin.
            from .cycle import cycle_tier_on, find_cycles

            if consistency == "session" and cycle_tier_on():
                try:
                    [c] = find_cycles([orig], model, kernel=False)
                except Exception:
                    c = None
                if c is not None and "cycle" in c:
                    res["sc-refuted"] = True
                    res["sc-cycle"] = c["cycle"]
                elif c is not None and "skipped-size" in c:
                    res["cycle-skipped-size"] = c["skipped-size"]
            return res

        [enc], [certified], [tier] = apply_rung([enc], model, consistency)
        if certified:
            note_tier(tier)
            return annotate_session(
                {"valid?": VALID, "algorithm": "greedy-witness",
                 "op-count": enc.n_ops,
                 "concurrency-window": enc.n_slots,
                 "decided-tier": tier,
                 "consistency": consistency})
        if consistency == "sequential":
            # Exact cycle tier on the degrade path too (host DFS arm:
            # no device launch) — same verdict the frontier would
            # reach, decided without the search.
            from .cycle import cycle_tier_on, find_cycles

            if cycle_tier_on():
                [c] = find_cycles([orig], model, kernel=False)
                if c is not None and "cycle" in c:
                    note_tier("cycle")
                    return {"valid?": INVALID, "algorithm": "cycle",
                            "op-count": orig.n_ops,
                            "concurrency-window": orig.n_slots,
                            "decided-tier": "cycle",
                            "cycle": c["cycle"],
                            "exact-sc-refutation": True,
                            "consistency": consistency}
    if consistency == "linearizable" and lin_fastpath is not False \
            and lin_fastpath_on():
        # Lin-rung fast path, host flavor (ISSUE 14): a witness on the
        # un-relaxed stream is a lin witness, so a certified row skips
        # the (worst-case exponential) frontier search entirely. Same
        # gating bucket + abort budget as the device path's pass.
        from .consistency import certify_encoded

        sig = autotune.lin_fastpath_sig(type(model).__name__,
                                        enc.n_events)
        if autotune.lin_fastpath_route(sig):
            abort = lin_abort_steps()
            t0 = time.perf_counter()
            ok, tier, _ = certify_encoded(
                enc, model,
                max_steps=abort * max(enc.n_events, 1) if abort
                else None)
            dt = time.perf_counter() - t0
            autotune.lin_fastpath_observe(sig, rows=1, hits=int(ok),
                                          wall_s=dt)
            _fp_bump(rows_scanned=1, rows_certified=int(ok),
                     events_scanned=enc.n_events, certify_wall_s=dt)
            if ok:
                note_tier(tier + "@lin", wall_s=dt)
                return {"valid?": VALID, "algorithm": "greedy-witness",
                        "op-count": enc.n_ops,
                        "concurrency-window": enc.n_slots,
                        "decided-tier": tier + "@lin"}
        else:
            _fp_bump(rows_gated=1)
    r = _check_cpu(enc, model, witness, max_cpu_configs)
    if r.get("valid?") is UNKNOWN:
        r2 = _check_dfs(enc, model, witness, max_steps=DEFAULT_DFS_BUDGET)
        if r2["valid?"] is not UNKNOWN:
            r = r2
    if consistency != "linearizable":
        r = annotate_session(r)
        r["consistency"] = consistency
    return r


def _check_cpu(enc: EncodedHistory, model, witness: bool,
               max_configs: Optional[int] = DEFAULT_MAX_CPU_CONFIGS,
               note: bool = True) -> dict:
    t0 = time.perf_counter()
    try:
        r = check_encoded_cpu(enc, model, max_configs=max_configs,
                              witness=witness)
    except FrontierOverflow as e:
        return {"valid?": UNKNOWN, "algorithm": "cpu", "error": str(e)}
    if note:
        note_tier("host", wall_s=time.perf_counter() - t0)
    out = {
        "valid?": VALID if r.valid else INVALID,
        "algorithm": "cpu",
        "op-count": enc.n_ops,
        "concurrency-window": enc.n_slots,
        "configs-explored": r.configs_explored,
        "max-frontier": r.max_frontier,
        "decided-tier": "host",
    }
    if not r.valid:
        out["failing-op-index"] = r.failing_op_index
    if r.witness is not None:
        out["witness"] = r.witness
    return out


class LinearizableChecker(Checker):
    """Checker-protocol wrapper around `check_histories` for one history.
    ``consistency`` selects the ladder rung (checker/consistency.py)."""

    def __init__(self, model, algorithm: str = "auto",
                 n_configs: Optional[int] = None,
                 n_slots: Optional[int] = None,
                 max_cpu_configs: Optional[int] = DEFAULT_MAX_CPU_CONFIGS,
                 consistency: str = "linearizable"):
        self.model = model
        self.algorithm = algorithm
        self.n_configs = n_configs
        self.n_slots = n_slots
        self.max_cpu_configs = max_cpu_configs
        self.consistency = _normalize_rung(consistency)

    def check(self, test, history, opts=None) -> dict:
        from .counterexample import (attach_counterexample,
                                     write_counterexample_html)

        if not isinstance(history, History):
            history = History(history)
        hist = history.client_ops()
        # witness=True so the host engines produce the explanation during
        # the verdict run — attach_counterexample then only re-searches
        # when the kernel (verdict-only) was the decider.
        [result] = check_histories(
            [hist], self.model, self.algorithm, self.n_configs, self.n_slots,
            witness=True, max_cpu_configs=self.max_cpu_configs,
            consistency=self.consistency,
        )
        if result.get("valid?") is INVALID:
            attach_counterexample(result, hist, self.model,
                                  max_cpu_configs=self.max_cpu_configs,
                                  consistency=self.consistency)
            write_counterexample_html(result, hist,
                                      (test or {}).get("store_dir"),
                                      "counterexample.html")
        return result

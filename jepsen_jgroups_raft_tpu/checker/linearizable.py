"""Linearizability checker with selectable execution backend.

Equivalent of `jepsen.checker/linearizable {:model m :algorithm ...}`
(reference register.clj:106-111, counter.clj:133-137) with the north-star
addition: algorithm ``"jax"`` runs the search on TPU (BASELINE.json —
"`jepsen.checker/linearizable` gains an `:algorithm :jax` option behind the
existing Checker protocol").

Algorithms:
  * ``"jax"``  — pack to event tensors, run the on-device frontier kernel
                 (ops/linear_scan.py); batched across histories.
  * ``"cpu"``  — the unbounded host frontier search (wgl_cpu.py).
  * ``"auto"`` — jax when the history fits the kernel window, with sound
                 escalation: any verdict the kernel cannot certify
                 (window overflow, frontier overflow on an invalid result)
                 is re-checked on the CPU twin. This mirrors the
                 reference's algorithm-racing habit (knossos.competition,
                 raft_test.clj:26) — two engines, the trustworthy answer
                 wins.

Soundness contract: a kernel "valid" is always sound (only reachable
configurations are ever retained, so a surviving linearization is real); a
kernel "invalid" is sound unless the frontier overflowed its fixed capacity,
in which case we escalate instead of reporting.
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Optional, Sequence

import numpy as np

from ..history.ops import History
from ..history.packing import EncodedHistory, encode_history, pack_batch
from ..ops.linear_scan import (DEFAULT_N_CONFIGS, MAX_SLOTS, bucket_slots,
                               make_batch_checker)
from .base import Checker, INVALID, UNKNOWN, VALID
from .wgl_cpu import FrontierOverflow, check_encoded_cpu


#: Default CPU-frontier cap. The search is worst-case exponential in the
#: concurrency window (SURVEY.md §7.4.1); an unbounded fallback would hang
#: rather than answer on adversarial histories (e.g. 40 mutually-concurrent
#: writes). Capped, it reports "unknown" instead — the same stance the
#: reference community takes when knossos becomes "unfeasible to verify"
#: (reference doc/intro.md:35-41), but as a clean verdict, not an OOM.
DEFAULT_MAX_CPU_CONFIGS = 1 << 18


def check_histories(
    histories: Sequence[History],
    model,
    algorithm: str = "auto",
    n_configs: Optional[int] = None,
    n_slots: Optional[int] = None,
    witness: bool = False,
    max_cpu_configs: Optional[int] = DEFAULT_MAX_CPU_CONFIGS,
) -> list[dict]:
    """Check a batch of histories; returns one result dict per history.

    The batch is the unit of TPU work: all histories are packed, padded to a
    common event length, and verified in one vmapped kernel launch.
    n_configs/n_slots default to auto: the concurrency window is sized to
    the batch's real maximum (bucketed to 8/16/32) — per-event closure work
    scales with C×W, so a snug window is a direct kernel-speed win.
    """

    encs = [encode_history(h, model) for h in histories]
    results: list[Optional[dict]] = [None] * len(encs)

    if algorithm in ("jax", "auto"):
        cap = n_slots or MAX_SLOTS
        fits = [i for i, e in enumerate(encs)
                if e.n_slots <= cap and e.n_events > 0]
        trivial = [i for i, e in enumerate(encs) if e.n_events == 0]
        for i in trivial:
            results[i] = {"valid?": VALID, "algorithm": "trivial", "op-count": 0}
        if fits:
            eff_slots = n_slots or bucket_slots(
                max(encs[i].n_slots for i in fits)
            )
            # Capacity ladder: per-event work is linear in the frontier
            # capacity C, and a "valid" at small C is final (overflow can
            # only drop configurations, i.e. cause false-INVALID, never
            # false-VALID) — so run everything at a small C and re-run only
            # the overflowed minority at full capacity. Typical histories
            # (bounded concurrency window) decide on the first rung, ~4×
            # cheaper than launching everything at DEFAULT_N_CONFIGS.
            ladder = ([n_configs] if n_configs else
                      [64, DEFAULT_N_CONFIGS] if DEFAULT_N_CONFIGS > 64
                      else [DEFAULT_N_CONFIGS])
            remaining = fits
            for rung, eff_configs in enumerate(ladder):
                batch = pack_batch([encs[i] for i in remaining])
                kernel = make_batch_checker(model, eff_configs, eff_slots)
                # Bucket both compile-shape dims (batch, events) to powers
                # of two so repeated calls hit the jit cache instead of
                # recompiling per batch size. Pad rows/events are EV_PAD
                # no-ops.
                ev = batch["events"]
                B, E = ev.shape[0], ev.shape[1]
                B2, E2 = _bucket(B, 8), _bucket(E, 32)
                if (B2, E2) != (B, E):
                    padded = np.zeros((B2, E2, 5), dtype=np.int32)
                    padded[:B, :E] = ev
                    ev = padded
                t0 = time.perf_counter()
                with _maybe_profile():
                    ok, overflow = kernel(ev)
                ok, overflow = ok[:B], overflow[:B]
                ok = np.asarray(ok)
                overflow = np.asarray(overflow)
                dt = time.perf_counter() - t0
                escalate = []
                for j, i in enumerate(remaining):
                    if ok[j]:
                        results[i] = _jx(VALID, encs[i], dt / len(remaining))
                    elif not overflow[j]:
                        results[i] = _jx(INVALID, encs[i],
                                         dt / len(remaining))
                    elif rung + 1 < len(ladder):
                        escalate.append(i)
                    # else: overflowed at top capacity → undecided,
                    # fall through to CPU/unknown
                remaining = escalate
                if not remaining:
                    break
        undecided = [i for i, r in enumerate(results) if r is None]
        if algorithm == "jax":
            for i in undecided:
                results[i] = {
                    "valid?": UNKNOWN,
                    "algorithm": "jax",
                    "error": "kernel capacity exceeded "
                    f"(window {encs[i].n_slots} slots); "
                    "use algorithm='auto' or 'cpu'",
                }
            return results  # type: ignore[return-value]

    for i, r in enumerate(results):
        if r is None:
            results[i] = _check_cpu(encs[i], model, witness, max_cpu_configs)
    return results  # type: ignore[return-value]


def _maybe_profile():
    """XLA profiler hook (SURVEY.md §5.1): set JGRAFT_PROFILE_DIR to
    capture a TensorBoard-readable trace of the kernel launches."""
    profile_dir = os.environ.get("JGRAFT_PROFILE_DIR")
    if not profile_dir:
        return contextlib.nullcontext()
    import jax
    return jax.profiler.trace(profile_dir)


def _bucket(n: int, floor: int) -> int:
    b = floor
    while b < n:
        b *= 2
    return b


def _jx(valid, enc: EncodedHistory, secs: float) -> dict:
    return {
        "valid?": valid,
        "algorithm": "jax",
        "op-count": enc.n_ops,
        "concurrency-window": enc.n_slots,
        "time-s": secs,
    }


def _check_cpu(enc: EncodedHistory, model, witness: bool,
               max_configs: Optional[int] = DEFAULT_MAX_CPU_CONFIGS) -> dict:
    try:
        r = check_encoded_cpu(enc, model, max_configs=max_configs,
                              witness=witness)
    except FrontierOverflow as e:
        return {"valid?": UNKNOWN, "algorithm": "cpu", "error": str(e)}
    out = {
        "valid?": VALID if r.valid else INVALID,
        "algorithm": "cpu",
        "op-count": enc.n_ops,
        "concurrency-window": enc.n_slots,
        "configs-explored": r.configs_explored,
        "max-frontier": r.max_frontier,
    }
    if not r.valid:
        out["failing-op-index"] = r.failing_op_index
    if r.witness is not None:
        out["witness"] = r.witness
    return out


class LinearizableChecker(Checker):
    """Checker-protocol wrapper around `check_histories` for one history."""

    def __init__(self, model, algorithm: str = "auto",
                 n_configs: Optional[int] = None,
                 n_slots: Optional[int] = None,
                 max_cpu_configs: Optional[int] = DEFAULT_MAX_CPU_CONFIGS):
        self.model = model
        self.algorithm = algorithm
        self.n_configs = n_configs
        self.n_slots = n_slots
        self.max_cpu_configs = max_cpu_configs

    def check(self, test, history, opts=None) -> dict:
        if not isinstance(history, History):
            history = History(history)
        hist = history.client_ops()
        [result] = check_histories(
            [hist], self.model, self.algorithm, self.n_configs, self.n_slots,
            max_cpu_configs=self.max_cpu_configs,
        )
        return result

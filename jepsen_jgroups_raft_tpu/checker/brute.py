"""Brute-force linearizability oracle (tests only).

Definition-level checker: enumerate every subset of optional (info) ops and
every permutation of the chosen ops that respects real-time precedence, and
ask the model whether some order is sequentially legal. Exponential — used
by the test suite to validate the frontier search (CPU and TPU) on small
randomized histories. Mirrors the role knossos' own tiny golden histories
play in the reference (raft_test.clj, SURVEY.md §4).
"""

from __future__ import annotations

from itertools import combinations
from typing import Sequence, Union

from ..history.ops import History, Op, OpPair, pair_ops_indexed


def check_brute(history: Union[History, Sequence[Op]], model) -> bool:
    ops = list(history)
    items = []  # (inv_pos, res_pos, encoded)
    for ip, cp, inv, comp in pair_ops_indexed(ops):
        enc = model.encode_pair(OpPair(inv, comp))
        if enc is None:
            continue
        if enc.forced and cp < 0:
            # Same inconsistency encode_history rejects: a forced op must
            # have a completion; cp=-1 would order it before everything.
            raise ValueError(
                f"model {type(model).__name__} encoded a pair with no "
                f"completion as forced (invoke index {inv.index})")
        items.append((ip, cp if enc.forced else float("inf"), enc))

    forced = [it for it in items if it[2].forced]
    optional = [it for it in items if not it[2].forced]

    for r in range(len(optional) + 1):
        for chosen in combinations(optional, r):
            if _search(forced + list(chosen), model):
                return True
    return False


def _search(items, model) -> bool:
    """DFS over precedence-respecting permutations with model pruning."""

    n = len(items)
    if n == 0:
        return True

    def rec(remaining: frozenset, state) -> bool:
        if not remaining:
            return True
        for i in remaining:
            inv_i = items[i][0]
            # i may come next only if no remaining j finished before i began
            if any(items[j][1] < inv_i for j in remaining if j != i):
                continue
            e = items[i][2]
            state2, legal = model.step(state, e.f, e.a, e.b)
            if legal and rec(remaining - {i}, state2):
                return True
        return False

    return rec(frozenset(range(n)), model.init_state())
